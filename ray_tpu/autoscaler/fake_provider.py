"""Fake provider: "launches" node-agent processes on this machine.

Equivalent of the reference's FakeMultiNodeProvider
(reference: python/ray/autoscaler/_private/fake_multi_node/
node_provider.py) — the workhorse that lets autoscaler behavior be
tested end-to-end with real cluster membership but no cloud API.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Dict, List

from ray_tpu._private import node as node_mod
from ray_tpu.autoscaler.node_provider import NodeProvider, ProviderNode


class FakeMultiNodeProvider(NodeProvider):
    def __init__(self, session_dir: str, head_addr):
        self._session_dir = session_dir
        self._head_addr = head_addr
        self._lock = threading.Lock()
        self._counter = 0
        self._nodes: Dict[str, ProviderNode] = {}
        self._procs: Dict[str, node_mod.ProcessHandle] = {}

    def create_node(self, node_type: str, resources: Dict[str, float],
                    count: int = 1) -> List[ProviderNode]:
        out: List[ProviderNode] = []
        for _ in range(count):
            with self._lock:
                self._counter += 1
                pid = f"fake-{node_type}-{self._counter}"
            proc, info = node_mod.start_node_agent(
                self._session_dir, self._head_addr, dict(resources),
                tag=pid)
            node = ProviderNode(pid, node_type, info["node_id"])
            with self._lock:
                self._nodes[pid] = node
                self._procs[pid] = proc
            out.append(node)
        return out

    def terminate_node(self, provider_id: str) -> None:
        with self._lock:
            node = self._nodes.pop(provider_id, None)
            proc = self._procs.pop(provider_id, None)
        if proc is None:
            return
        # SIGTERM → graceful agent shutdown (workers die via PDEATHSIG)
        proc.terminate()

    def non_terminated_nodes(self) -> List[ProviderNode]:
        with self._lock:
            alive = []
            for pid, node in list(self._nodes.items()):
                proc = self._procs.get(pid)
                if proc is not None and proc.proc.poll() is None:
                    alive.append(node)
                else:
                    self._nodes.pop(pid, None)
                    self._procs.pop(pid, None)
            return alive

    def shutdown(self) -> None:
        for pid in [n.provider_id for n in self.non_terminated_nodes()]:
            self.terminate_node(pid)
