"""Demand-driven autoscaler v1.

Equivalent of the reference's StandardAutoscaler + ResourceDemandScheduler
(reference: python/ray/autoscaler/_private/autoscaler.py,
resource_demand_scheduler.py, monitor.py): a loop that

  1. reads the cluster's demand/supply snapshot from the head
     (queued + parked-infeasible lease demands, PENDING placement-group
     bundles, PENDING actors — the same three demand sources the
     reference bin-packs from load_metrics),
  2. bin-packs unmet demand into `available_node_types` and launches
     what's missing through a NodeProvider,
  3. drains and terminates nodes that have sat idle past the timeout
     (never below min_workers, never the head node).

TPU slices are atomic launch groups: a node type with ``launch_group: k``
always launches k hosts together (one ICI-connected slice), mirroring
how the reference's GCPTPU provider brings up whole TPU pods
(reference: gcp/node.py:191, tpu_command_runner.py fans to all hosts).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private.resources import ResourceSet
from ray_tpu._private.rpc import EventLoopThread, SyncRpcClient
from ray_tpu.autoscaler.node_provider import NodeProvider, ProviderNode


class AutoscalerConfig:
    def __init__(self, node_types: Dict[str, Dict[str, Any]],
                 idle_timeout_s: float = 60.0,
                 update_period_s: float = 1.0):
        """node_types: {name: {"resources": {...}, "min_workers": 0,
        "max_workers": N, "launch_group": 1}}"""
        self.node_types = node_types
        self.idle_timeout_s = idle_timeout_s
        self.update_period_s = update_period_s


class StandardAutoscaler:
    def __init__(self, head_addr, provider: NodeProvider,
                 config: AutoscalerConfig):
        self.provider = provider
        self.config = config
        self._io = EventLoopThread(name="autoscaler-io")
        self.head = SyncRpcClient(head_addr[0], head_addr[1], self._io,
                                  label="head", retry_lost_s=15.0)
        self._idle_since: Dict[str, float] = {}  # cluster node id -> t
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registration = {
            name: {"resources": t.get("resources", {})}
            for name, t in config.node_types.items()}
        self.head.call("register_autoscaler", node_types=self._registration)

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.head.close()
        self._io.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.config.update_period_s):
            try:
                self.update()
            except Exception:
                import traceback

                traceback.print_exc()

    # ---- one reconcile pass ------------------------------------------------

    def update(self) -> None:
        # idempotent re-registration: a restarted head relearns the node
        # types it can ask us for within one pass
        self.head.call("register_autoscaler", node_types=self._registration)
        state = self.head.call("autoscaler_state")
        demands = self._collect_demands(state)
        unmet = self._fit_on_existing(state, demands)
        self._scale_up(unmet)
        self._enforce_min_workers()
        self._scale_down(state)

    def _collect_demands(self, state) -> List[ResourceSet]:
        demands: List[ResourceSet] = []
        for n in state["nodes"]:
            demands.extend(ResourceSet(d) for d in n["pending"])
        demands.extend(ResourceSet(b["resources"])
                       for b in state["pending_pg_bundles"])
        demands.extend(ResourceSet(d) for d in state["pending_actors"])
        return demands

    def _fit_on_existing(self, state, demands: List[ResourceSet]
                         ) -> List[ResourceSet]:
        """First-fit-decreasing onto current availability; the leftovers
        are what new capacity must cover."""
        frees = [ResourceSet(n["available"]) for n in state["nodes"]
                 if n["heartbeat_age_s"] < 30.0]
        unmet: List[ResourceSet] = []
        for d in sorted(demands, key=lambda r: -sum(r.to_dict().values())):
            for i, free in enumerate(frees):
                if free.fits(d):
                    frees[i] = free.subtract(d)
                    break
            else:
                unmet.append(d)
        return unmet

    def _counts_by_type(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for node in self.provider.non_terminated_nodes():
            counts[node.node_type] = counts.get(node.node_type, 0) + 1
        return counts

    def _scale_up(self, unmet: List[ResourceSet]) -> None:
        if not unmet:
            return
        counts = self._counts_by_type()
        planned: List[List[Any]] = []  # [node_type, remaining ResourceSet]
        to_launch: Dict[str, int] = {}
        for d in unmet:
            placed = False
            for p in planned:
                if p[1].fits(d):
                    p[1] = p[1].subtract(d)
                    placed = True
                    break
            if placed:
                continue
            for name, t in self.config.node_types.items():
                shape = ResourceSet(t.get("resources", {}))
                if not shape.fits(d):
                    continue
                group = max(1, int(t.get("launch_group", 1)))
                have = counts.get(name, 0) + to_launch.get(name, 0)
                if have + group > int(t.get("max_workers", 8)):
                    continue
                to_launch[name] = to_launch.get(name, 0) + group
                fresh = [[name, ResourceSet(t.get("resources", {}))]
                         for _ in range(group)]
                fresh[0][1] = fresh[0][1].subtract(d)
                planned.extend(fresh)
                break
            # no type fits: the demand is truly infeasible — the agent
            # will fail it through the normal infeasible path
        for name, count in to_launch.items():
            t = self.config.node_types[name]
            self.provider.create_node(name, dict(t.get("resources", {})),
                                      count)

    def _enforce_min_workers(self) -> None:
        counts = self._counts_by_type()
        for name, t in self.config.node_types.items():
            deficit = int(t.get("min_workers", 0)) - counts.get(name, 0)
            if deficit > 0:
                self.provider.create_node(
                    name, dict(t.get("resources", {})), deficit)

    def _scale_down(self, state) -> None:
        now = time.monotonic()
        by_cluster_id: Dict[str, ProviderNode] = {
            n.cluster_node_id: n
            for n in self.provider.non_terminated_nodes()
            if n.cluster_node_id}
        counts = self._counts_by_type()
        live_ids = set()
        for n in state["nodes"]:
            nid = n["node_id"]
            live_ids.add(nid)
            pnode = by_cluster_id.get(nid)
            if pnode is None or n["is_head_node"]:
                continue
            busy = (n["pending"]
                    or ResourceSet(n["total"]) != ResourceSet(n["available"]))
            if busy:
                self._idle_since.pop(nid, None)
                continue
            since = self._idle_since.setdefault(nid, now)
            t = self.config.node_types.get(pnode.node_type, {})
            if (now - since >= self.config.idle_timeout_s
                    and counts.get(pnode.node_type, 0)
                    > int(t.get("min_workers", 0))):
                try:
                    self.head.call("drain_node", node_id=nid)
                except Exception:
                    pass
                self.provider.terminate_node(pnode.provider_id)
                self._idle_since.pop(nid, None)
                counts[pnode.node_type] = counts.get(pnode.node_type, 1) - 1
        self._idle_since = {k: v for k, v in self._idle_since.items()
                            if k in live_ids}
