"""Signal-driven elastic autoscaler (v2).

Equivalent of the reference's StandardAutoscaler + ResourceDemandScheduler
+ monitor loop (reference: python/ray/autoscaler/_private/autoscaler.py,
resource_demand_scheduler.py, monitor.py), grown from the v1 raw-queue
poll into a subsystem wired through the head:

  1. each pass reads the head's **autoscaler snapshot** — queued +
     parked-infeasible lease demands, PENDING placement-group bundles
     and PENDING actors (the three demand sources the reference
     bin-packs from load_metrics), PLUS the signals earlier subsystems
     built: lease-queue-depth trends off the PR-6 time-series ring,
     scheduler-latency p99 off the task-event store, per-node store
     byte breakdowns off PR-9 memory accounting, and Serve/LLM queue
     pressure off the heartbeat gauge summaries;
  2. demand NO existing node can ever fit launches immediately (waiting
     cannot resolve infeasibility — reference: upscaling on infeasible
     resource requests); demand that merely queues behind busy capacity
     (backlog) must be SUSTAINED for ``autoscaler_upscale_consecutive``
     passes before nodes launch — one spike that drains on its own
     must not thrash the cluster (hysteresis);
  3. scale-down is **drain-based**: an idle node past the timeout is
     handed to the head's graceful drain state machine
     (rpc_drain_node_graceful: lease quiesce, ``__rt_save__`` actor
     migration, sole-primary-copy re-replication) and the provider
     only terminates it after the head reports ``drained`` — never
     below min_workers, never the head node.  The drain victim is the
     idle node holding the FEWEST store bytes (cheapest
     re-replication, from the PR-9 breakdowns).

TPU slices stay atomic launch groups (``launch_group: k`` launches k
hosts together; reference: gcp/node.py GCPTPU pod bring-up), and
launches run on background threads tracked as *pending* so a slow boot
never stalls the decision loop.  ``stop()`` is idempotent; in-flight
launches are joined briefly and otherwise ADOPTED — the provider tracks
their nodes, so a successor autoscaler (or shutdown()) finds them.

The head handshake follows the DeltaReporter epoch pattern: the
snapshot carries the head's boot epoch, and a change (head restart)
triggers node-type re-registration within one pass.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.config import config
from ray_tpu._private.resources import ResourceSet
from ray_tpu.autoscaler.node_provider import NodeProvider, ProviderNode


class AutoscalerConfig:
    def __init__(self, node_types: Dict[str, Dict[str, Any]],
                 idle_timeout_s: float = 60.0,
                 update_period_s: float = 1.0,
                 upscale_consecutive: Optional[int] = None,
                 sched_p99_threshold_ms: Optional[float] = None):
        """node_types: {name: {"resources": {...}, "min_workers": 0,
        "max_workers": N, "launch_group": 1}}"""
        self.node_types = node_types
        self.idle_timeout_s = idle_timeout_s
        self.update_period_s = update_period_s
        # backlog hysteresis: consecutive passes of sustained feasible-
        # but-queued demand before it may launch capacity
        self.upscale_consecutive = int(
            upscale_consecutive if upscale_consecutive is not None
            else config.autoscaler_upscale_consecutive)
        # scheduler-latency SLO pressure (0 disables)
        self.sched_p99_threshold_ms = float(
            sched_p99_threshold_ms if sched_p99_threshold_ms is not None
            else config.autoscaler_sched_p99_threshold_ms)


class _PendingLaunch:
    __slots__ = ("node_type", "count", "started", "thread", "done",
                 "nodes")

    def __init__(self, node_type: str, count: int, started: float,
                 thread: threading.Thread):
        self.node_type = node_type
        self.count = count
        self.started = started
        self.thread = thread
        self.done = False  # create_node returned
        self.nodes: List[ProviderNode] = []


class StandardAutoscaler:
    def __init__(self, head_addr, provider: NodeProvider,
                 config: AutoscalerConfig, *,
                 head_client: Any = None,
                 clock: Callable[[], float] = time.monotonic):
        cfg = config
        self.provider = provider
        self.config = cfg
        self.clock = clock  # injectable for deterministic unit tests
        self._io = None
        if head_client is not None:
            self.head = head_client
        else:
            from ray_tpu._private.rpc import EventLoopThread, SyncRpcClient

            self._io = EventLoopThread(name="autoscaler-io")
            self.head = SyncRpcClient(head_addr[0], head_addr[1], self._io,
                                      label="head", retry_lost_s=15.0)
        self._idle_since: Dict[str, float] = {}  # cluster node id -> t
        self._stop = threading.Event()
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._pending: List[_PendingLaunch] = []
        # node_id (cluster) -> provider_id being drained right now
        self._draining: Dict[str, str] = {}
        # backlog hysteresis: consecutive passes with unmet-but-feasible
        # demand, per demand shape key
        self._backlog_streak: Dict[str, int] = {}
        self._slo_streak = 0
        self._last_decision = "startup"
        self._events_delta = {"up": 0, "down": 0}
        self.scale_up_total = 0
        self.scale_down_total = 0
        self._registration = {
            name: {"resources": t.get("resources", {})}
            for name, t in cfg.node_types.items()}
        # register synchronously at construction — work submitted the
        # moment the cluster is up must see the scalable shapes, not
        # fail infeasible — and learn the head's boot epoch from the
        # reply; a later epoch CHANGE in the snapshot (head restart)
        # re-registers within one pass (DeltaReporter handshake)
        self._seen_epoch: Optional[str] = None
        self._register()

    # ---- lifecycle ---------------------------------------------------------

    def _register(self) -> None:
        try:
            reply = self.head.call("register_autoscaler",
                                   node_types=self._registration)
            self._seen_epoch = reply.get("epoch") or self._seen_epoch
        except Exception:
            pass  # head briefly unreachable: retried on epoch mismatch

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Idempotent shutdown.  In-flight launches are joined briefly;
        ones still running are ADOPTED — their threads only register
        nodes with the provider, which a successor autoscaler (or
        provider.shutdown()) observes via non_terminated_nodes()."""
        if self._stopped:
            return
        self._stopped = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._lock:
            pending = list(self._pending)
        for p in pending:
            p.thread.join(timeout=2)
        try:
            self.head.close()
        except Exception:
            pass
        if self._io is not None:
            self._io.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.config.update_period_s):
            try:
                self.update()
            except Exception:
                import traceback

                traceback.print_exc()

    # ---- one reconcile pass ------------------------------------------------

    def update(self) -> None:
        state = self.head.call("autoscaler_snapshot")
        epoch = state.get("epoch")
        if epoch != self._seen_epoch:
            # head restarted (or first contact): it lost the registered
            # node types — re-register before acting on the snapshot
            # (epoch-handshake, the DeltaReporter pattern).  _register
            # adopts the epoch only from a SUCCESSFUL reply, so a
            # transient registration failure retries next pass instead
            # of leaving the head typeless until its next restart
            self._register()
        self._reap_pending(state)
        demands = self._collect_demands(state)
        unmet, infeasible_now = self._split_unmet(state, demands)
        backlog = self._sustained_backlog(unmet, state)
        to_launch = self._plan_scale_up(infeasible_now + backlog)
        if to_launch:
            self._last_decision = (
                f"scale up {to_launch} "
                f"({len(infeasible_now)} infeasible, "
                f"{len(backlog)} sustained-backlog demands)")
        self._enforce_min_workers()
        self._advance_drains(state)
        self._scale_down(state, demands)
        self._report()

    # ---- demand plane ------------------------------------------------------

    def _collect_demands(self, state) -> List[ResourceSet]:
        demands: List[ResourceSet] = []
        for n in state["nodes"]:
            if n.get("draining"):
                continue
            demands.extend(ResourceSet(d) for d in n["pending"])
        demands.extend(ResourceSet(b["resources"])
                       for b in state["pending_pg_bundles"])
        demands.extend(ResourceSet(d) for d in state["pending_actors"])
        return demands

    def _split_unmet(self, state, demands: List[ResourceSet]):
        """First-fit-decreasing onto current availability.  Leftovers
        split into (backlog, infeasible-now): a demand NO live node's
        TOTALS fit can never run on the current fleet and scales up
        immediately; one that merely doesn't fit current *availability*
        is backlog and goes through hysteresis."""
        live = [n for n in state["nodes"]
                if n["heartbeat_age_s"] < 30.0 and not n.get("draining")]
        frees = [ResourceSet(n["available"]) for n in live]
        totals = [ResourceSet(n["total"]) for n in live]
        backlog: List[ResourceSet] = []
        infeasible: List[ResourceSet] = []
        for d in sorted(demands, key=lambda r: -sum(r.to_dict().values())):
            for i, free in enumerate(frees):
                if free.fits(d):
                    frees[i] = free.subtract(d)
                    break
            else:
                if any(t.fits(d) for t in totals):
                    backlog.append(d)
                else:
                    infeasible.append(d)
        return backlog, infeasible

    def _sustained_backlog(self, backlog: List[ResourceSet],
                           state) -> List[ResourceSet]:
        """Hysteresis: feasible-but-queued demand only counts once it
        has persisted for ``upscale_consecutive`` passes, corroborated
        by the head's lease-queue-depth ring staying non-empty (trend
        smoothing — a single spike whose queue already drained never
        launches).  Scheduler-latency p99 over the configured SLO
        behaves like one extra backlog demand of the largest shape."""
        signals = state.get("signals") or {}
        ring = signals.get("lease_queue_depth") or {}
        # the ring only sees demand that reached an agent's lease queue;
        # head-parked demand (PENDING actors, unplaced PG bundles) never
        # does, yet its very presence in the CURRENT snapshot is live
        # pressure — without this, a pending actor whose shape fits a
        # busy node's totals would never convert its streak to a launch
        queue_live = (any(vals and vals[-1] > 0 for vals in ring.values())
                      or bool(state.get("pending_actors"))
                      or bool(state.get("pending_pg_bundles")))
        keys_seen = set()
        sustained: List[ResourceSet] = []
        for d in backlog:
            key = repr(sorted(d.to_dict().items()))
            keys_seen.add(key)
            streak = self._backlog_streak.get(key, 0) + 1
            self._backlog_streak[key] = streak
            if streak >= self.config.upscale_consecutive \
                    and (queue_live or not ring):
                sustained.append(d)
        # streaks of shapes no longer queued reset — hysteresis measures
        # CONSECUTIVE pressure
        self._backlog_streak = {k: v for k, v
                                in self._backlog_streak.items()
                                if k in keys_seen}
        thresh = self.config.sched_p99_threshold_ms
        p99 = float(signals.get("sched_queued_p99_ms") or 0.0)
        if thresh > 0 and p99 > thresh:
            self._slo_streak += 1
            if self._slo_streak >= self.config.upscale_consecutive \
                    and not sustained and self.config.node_types:
                first = next(iter(self.config.node_types.values()))
                sustained.append(ResourceSet(first.get("resources", {})))
        else:
            self._slo_streak = 0
        return sustained

    # ---- scale up ----------------------------------------------------------

    def _counts_by_type(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for node in self.provider.non_terminated_nodes():
            counts[node.node_type] = counts.get(node.node_type, 0) + 1
        with self._lock:
            for p in self._pending:
                if not p.done:  # done launches already show in provider
                    counts[p.node_type] = \
                        counts.get(p.node_type, 0) + p.count
        return counts

    def _plan_scale_up(self, unmet: List[ResourceSet]) -> Dict[str, int]:
        if not unmet:
            return {}
        counts = self._counts_by_type()
        # capacity already in flight covers demand first: a launch takes
        # several passes to boot + register, and re-launching for the
        # same pending demand every pass would churn nodes (the async
        # cousin of v1's blocking create_node, which hid this window)
        planned: List[List[Any]] = []  # [node_type, remaining ResourceSet]
        with self._lock:
            for p in self._pending:
                shape = self.config.node_types.get(
                    p.node_type, {}).get("resources", {})
                for _ in range(p.count):
                    planned.append([p.node_type, ResourceSet(shape)])
        to_launch: Dict[str, int] = {}
        for d in unmet:
            placed = False
            for p in planned:
                if p[1].fits(d):
                    p[1] = p[1].subtract(d)
                    placed = True
                    break
            if placed:
                continue
            for name, t in self.config.node_types.items():
                shape = ResourceSet(t.get("resources", {}))
                if not shape.fits(d):
                    continue
                group = max(1, int(t.get("launch_group", 1)))
                have = counts.get(name, 0) + to_launch.get(name, 0)
                if have + group > int(t.get("max_workers", 8)):
                    continue
                to_launch[name] = to_launch.get(name, 0) + group
                fresh = [[name, ResourceSet(t.get("resources", {}))]
                         for _ in range(group)]
                fresh[0][1] = fresh[0][1].subtract(d)
                planned.extend(fresh)
                break
            # no type fits: the demand is truly infeasible — the agent
            # fails it through the normal infeasible path
        for name, count in to_launch.items():
            self._launch(name, count)
        return to_launch

    def _launch(self, name: str, count: int) -> None:
        """Background launch so a slow provider boot (subprocess spawn,
        cloud API) never stalls the decision loop; tracked as pending
        both for max_workers accounting and `rtpu status`."""
        t = self.config.node_types[name]
        resources = dict(t.get("resources", {}))
        pending = _PendingLaunch(name, count, self.clock(), None)

        def run():
            try:
                pending.nodes = self.provider.create_node(name, resources,
                                                          count)
                with self._lock:
                    # per NODE, symmetric with per-node drain counting
                    self.scale_up_total += count
                    self._events_delta["up"] += count
                # stays in _pending until its nodes REGISTER (appear in
                # the head snapshot): the launch keeps covering its
                # demand across the boot->register->snapshot staleness
                # window (see _reap_pending)
                pending.done = True
            except Exception:
                import traceback

                traceback.print_exc()
                with self._lock:
                    if pending in self._pending:
                        self._pending.remove(pending)

        pending.thread = threading.Thread(
            target=run, name=f"autoscaler-launch-{name}", daemon=True)
        with self._lock:
            self._pending.append(pending)
        pending.thread.start()

    def _reap_pending(self, state) -> None:
        """A launch stops being 'pending' once every node it created is
        REGISTERED (visible in the head snapshot) — only then does the
        demand it covered show against real availability.  A 60s
        backstop reaps launches whose nodes never made it (boot crash),
        so their capacity stops masking still-unmet demand forever."""
        seen = {n["node_id"] for n in state.get("nodes", ())}
        now = self.clock()
        with self._lock:
            kept = []
            for p in self._pending:
                if p.done and all(n.cluster_node_id in seen
                                  for n in p.nodes):
                    continue
                if now - p.started > 60.0 and not p.thread.is_alive():
                    continue
                kept.append(p)
            self._pending = kept

    def _enforce_min_workers(self) -> None:
        counts = self._counts_by_type()
        for name, t in self.config.node_types.items():
            deficit = int(t.get("min_workers", 0)) - counts.get(name, 0)
            if deficit > 0:
                self._launch(name, deficit)

    # ---- drain-based scale down -------------------------------------------

    def _store_bytes(self, state, node_id: str) -> int:
        for n in state["nodes"]:
            if n["node_id"] == node_id:
                return int((n.get("memory") or {}).get("arena_used", 0))
        return 0

    def _scale_down(self, state,
                    cluster_pending: List[ResourceSet]) -> None:
        now = self.clock()
        by_cluster_id: Dict[str, ProviderNode] = {
            n.cluster_node_id: n
            for n in self.provider.non_terminated_nodes()
            if n.cluster_node_id}
        counts = self._counts_by_type()
        live_ids = set()
        # pass 1: refresh idle clocks
        idle_candidates: List[str] = []
        for n in state["nodes"]:
            nid = n["node_id"]
            live_ids.add(nid)
            pnode = by_cluster_id.get(nid)
            if pnode is None or n["is_head_node"] or n.get("draining") \
                    or nid in self._draining:
                continue
            total = ResourceSet(n["total"])
            # cluster-pending demand (parked actors, unplaced PG
            # bundles, queued leases): an idle node whose TOTALS fit any
            # of it was probably just launched FOR it — draining would
            # churn
            busy = (n["pending"]
                    or total != ResourceSet(n["available"])
                    or any(total.fits(d) for d in cluster_pending))
            if busy:
                self._idle_since.pop(nid, None)
                continue
            since = self._idle_since.setdefault(nid, now)
            t = self.config.node_types.get(pnode.node_type, {})
            if (now - since >= self.config.idle_timeout_s
                    and counts.get(pnode.node_type, 0)
                    - sum(1 for d_nid, _pid in self._draining.items()
                          if by_cluster_id.get(d_nid) is not None
                          and by_cluster_id[d_nid].node_type
                          == pnode.node_type)
                    > int(t.get("min_workers", 0))):
                idle_candidates.append(nid)
        # pass 2: ONE drain victim per pass — the idle node with the
        # fewest stored bytes (cheapest re-replication per the PR-9
        # byte breakdowns); serializing drains keeps re-replication
        # targets plentiful and the accounting simple
        if idle_candidates and not self._draining:
            victim = min(idle_candidates,
                         key=lambda nid: self._store_bytes(state, nid))
            try:
                r = self.head.call("drain_node_graceful", node_id=victim)
            except Exception:
                r = {"ok": False}
            if r.get("ok"):
                self._draining[victim] = by_cluster_id[victim].provider_id
                self._idle_since.pop(victim, None)
                self._last_decision = f"draining idle node {victim[:12]}"
        self._idle_since = {k: v for k, v in self._idle_since.items()
                            if k in live_ids}

    def _advance_drains(self, state) -> None:
        """Terminate provider nodes whose graceful drain completed; a
        failed drain releases the node back to service (the head
        already cleared its draining flag)."""
        drains = state.get("drains") or {}
        for nid, pid in list(self._draining.items()):
            rec = drains.get(nid)
            if rec is None:
                try:
                    rec = self.head.call("drain_status", node_id=nid)
                except Exception:
                    continue
            st = rec.get("state")
            if st == "drained":
                self.provider.terminate_node(pid)
                with self._lock:
                    self.scale_down_total += 1
                    self._events_delta["down"] += 1
                self._draining.pop(nid, None)
                self._last_decision = f"drained + terminated {nid[:12]}"
            elif st in ("failed", "none"):
                self._draining.pop(nid, None)
                self._last_decision = (
                    f"drain of {nid[:12]} {st}: "
                    f"{rec.get('detail', '')}"[:120])

    # ---- status ------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        counts = self._provider_counts()
        # everything mutable reads under the lock: status() is called
        # from foreign threads (bench, AutoscalingCluster.status) while
        # the autoscaler thread mutates these
        with self._lock:
            return {
                "pending_launches": sum(p.count for p in self._pending),
                "draining": list(self._draining),
                "last_decision": self._last_decision,
                "scale_up_total": self.scale_up_total,
                "scale_down_total": self.scale_down_total,
                "node_counts": counts,
            }

    def _provider_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        try:
            for node in self.provider.non_terminated_nodes():
                counts[node.node_type] = counts.get(node.node_type, 0) + 1
        except Exception:
            pass
        return counts

    def _report(self) -> None:
        """Push this pass's status to the head (best-effort): the
        debuggability surface behind /api/autoscaler and `rtpu status`,
        plus scale-event deltas for the head-side counter."""
        st = self.status()
        with self._lock:
            delta = dict(self._events_delta)
        st["events_delta"] = delta
        try:
            self.head.call("autoscaler_report", status=st)
        except Exception:
            return  # unreported deltas carry to the next pass
        with self._lock:
            for k, v in delta.items():
                self._events_delta[k] -= v
