from ray_tpu.autoscaler.autoscaler import (AutoscalerConfig,  # noqa: F401
                                           StandardAutoscaler)
from ray_tpu.autoscaler.fake_provider import \
    FakeMultiNodeProvider  # noqa: F401
from ray_tpu.autoscaler.node_provider import (NodeProvider,  # noqa: F401
                                              ProviderNode)
