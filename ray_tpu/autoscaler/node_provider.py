"""Node provider abstraction: how the autoscaler launches real capacity.

Equivalent of the reference's NodeProvider
(reference: python/ray/autoscaler/node_provider.py — create_node,
terminate_node, non_terminated_nodes), reduced to what the demand loop
needs.  Cloud providers (GCE/GKE TPU; reference:
python/ray/autoscaler/_private/gcp/node.py:191 GCPTPU) implement this
against their VM/TPU APIs; tests use FakeMultiNodeProvider, which
spawns local node-agent processes (reference:
_private/fake_multi_node/node_provider.py).

A TPU slice is modelled as an atomic launch group: `create_node` for a
type with ``launch_group: k`` brings up k ICI-connected hosts together
or not at all — the provider-level face of slice gang scheduling.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional


class ProviderNode:
    """One provider-managed node (a VM / TPU host / local process)."""

    __slots__ = ("provider_id", "node_type", "cluster_node_id")

    def __init__(self, provider_id: str, node_type: str,
                 cluster_node_id: Optional[str] = None):
        self.provider_id = provider_id
        self.node_type = node_type
        # the node id the agent registered with the head (None until the
        # node has booted far enough to know it)
        self.cluster_node_id = cluster_node_id


class NodeProvider(ABC):
    @abstractmethod
    def create_node(self, node_type: str, resources: Dict[str, float],
                    count: int = 1) -> List[ProviderNode]:
        """Launch `count` nodes of `node_type`.  Blocking providers may
        return booted nodes; async providers may return placeholders
        that fill in cluster_node_id later."""

    @abstractmethod
    def terminate_node(self, provider_id: str) -> None:
        """Tear one node down."""

    @abstractmethod
    def non_terminated_nodes(self) -> List[ProviderNode]:
        """All nodes this provider currently manages."""
