"""Multi-node clusters on one machine, for tests and local development.

Equivalent of the reference's cluster_utils.Cluster
(reference: python/ray/cluster_utils.py:135 — add_node :201,
remove_node :274): spawns one head service plus N node agents as real
processes; `remove_node` SIGKILLs an agent (its workers die with it via
PDEATHSIG), which is the node-failure injection used by fault-tolerance
tests (reference: test_utils.py:1497 NodeKillerActor).
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import node as node_mod


class NodeHandle:
    def __init__(self, proc, info: Dict[str, Any]):
        self.proc = proc
        self.node_id: str = info["node_id"]
        self.addr = info["addr"]
        self.arena_path: str = info["arena_path"]

    @property
    def alive(self) -> bool:
        return self.proc.proc.poll() is None


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict[str, Any]] = None):
        self.session_dir = node_mod.new_session_dir()
        self._head_proc, self.head_addr = node_mod.start_head(self.session_dir)
        self.nodes: List[NodeHandle] = []
        if initialize_head:
            self.add_node(is_head_node=True, **(head_node_args or {}))

    @property
    def address(self) -> str:
        return f"{self.head_addr[0]}:{self.head_addr[1]}"

    def add_node(self, num_cpus: float = 4,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: Optional[int] = None,
                 is_head_node: bool = False,
                 labels: Optional[Dict[str, str]] = None) -> NodeHandle:
        res: Dict[str, float] = {"CPU": float(num_cpus)}
        if resources:
            res.update(resources)
        proc, info = node_mod.start_node_agent(
            self.session_dir, self.head_addr, res,
            object_store_memory=object_store_memory,
            is_head_node=is_head_node, labels=labels,
            tag=f"agent-{len(self.nodes)}")
        handle = NodeHandle(proc, info)
        self.nodes.append(handle)
        return handle

    def remove_node(self, node: NodeHandle, graceful: bool = False,
                    allow_graceful_fallback: bool = True) -> None:
        """Kill a node. Non-graceful = SIGKILL the agent (workers die via
        PDEATHSIG); the head notices via connection drop."""
        if graceful:
            node.proc.terminate()
        else:
            try:
                os.kill(node.proc.proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                node.proc.proc.wait(timeout=5)
            except Exception:
                pass
        if node in self.nodes:
            self.nodes.remove(node)

    def restart_head(self, kill: bool = True) -> None:
        """Kill the head process and restart it on the SAME port from its
        persisted state (reference: GCS fault tolerance via Redis-backed
        store, tests/test_gcs_fault_tolerance.py).  Agents re-register on
        their next heartbeat; drivers ride out the window via the head
        client's retry-on-connection-loss."""
        port = self.head_addr[1]
        if kill:
            try:
                os.kill(self._head_proc.proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        else:
            self._head_proc.terminate()
        try:
            self._head_proc.proc.wait(timeout=5)
        except Exception:
            pass
        self._head_proc, self.head_addr = node_mod.start_head(
            self.session_dir, port=port)

    def wait_for_nodes(self, count: Optional[int] = None,
                       timeout: float = 30.0) -> None:
        """Block until the head's node table has `count` live entries."""
        import ray_tpu

        expect = count if count is not None else len(self.nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if len(ray_tpu.nodes()) == expect:
                    return
            except Exception:
                pass
            time.sleep(0.1)
        raise TimeoutError(f"cluster did not reach {expect} nodes")

    def shutdown(self) -> None:
        for node in list(self.nodes):
            node.proc.terminate()
        self.nodes = []
        self._head_proc.terminate()


class AutoscalingCluster:
    """A head node plus an autoscaler over the fake provider — scale-up/
    down testable on one machine (reference: cluster_utils.py:26
    AutoscalingCluster + FakeMultiNodeProvider)."""

    def __init__(self, head_resources: Optional[Dict[str, float]] = None,
                 worker_node_types: Optional[Dict[str, Any]] = None,
                 idle_timeout_s: float = 60.0,
                 update_period_s: float = 0.5,
                 upscale_consecutive: Optional[int] = None,
                 sched_p99_threshold_ms: Optional[float] = None):
        from ray_tpu.autoscaler import (AutoscalerConfig,
                                        FakeMultiNodeProvider,
                                        StandardAutoscaler)

        head_resources = head_resources or {"CPU": 2}
        self.cluster = Cluster(initialize_head=True, head_node_args={
            "num_cpus": head_resources.get("CPU", 2),
            "resources": {k: v for k, v in head_resources.items()
                          if k != "CPU"}})
        self.provider = FakeMultiNodeProvider(
            self.cluster.session_dir, self.cluster.head_addr)
        self.autoscaler = StandardAutoscaler(
            self.cluster.head_addr, self.provider,
            AutoscalerConfig(worker_node_types or {},
                             idle_timeout_s=idle_timeout_s,
                             update_period_s=update_period_s,
                             upscale_consecutive=upscale_consecutive,
                             sched_p99_threshold_ms=sched_p99_threshold_ms))
        self.autoscaler.start()

    @property
    def address(self) -> str:
        return self.cluster.address

    def status(self) -> Dict[str, Any]:
        """The autoscaler's live status (pending launches, draining
        nodes, last decision) — what /api/autoscaler serves."""
        return self.autoscaler.status()

    def shutdown(self) -> None:
        self.autoscaler.stop()
        self.provider.shutdown()
        self.cluster.shutdown()
