"""Host-side collective communication between workers/actors.

Equivalent of the reference's ray.util.collective API
(reference: python/ray/util/collective/collective.py —
init_collective_group :120, allreduce :258, barrier :298, reduce :311,
broadcast :373, allgather :423, reducescatter :472, send/recv :531/:594).

Backend split, TPU-style (SURVEY §5.8): accelerator-plane collectives are
XLA collectives (jax.lax.psum/all_gather/ppermute) compiled over ICI
inside jit — NOT this module.  This module is the *host/control plane*:
small numpy payloads (rendezvous info, metrics, barriers) between worker
processes, riding the same RPC plane as tasks.  Rendezvous is the head's
KV (reference uses a named actor storing the NCCL unique id).

Topology: gather-to-root + broadcast (2(N-1) messages).  Payloads here
are control-sized; bulk tensors belong on the object store or in XLA
collectives.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_groups: Dict[str, "_Group"] = {}
_groups_lock = threading.Lock()
# messages that arrived before their group was initialized locally
_undelivered: Dict[str, List[Tuple[str, int, int, bytes, float]]] = {}


class _Group:
    def __init__(self, name: str, world_size: int, rank: int):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.members: List[Tuple[str, int]] = []  # rank -> worker RPC addr
        self.seq = 0                              # collective-op sequence
        self.p2p_send: Dict[int, int] = {}        # dst -> seq (per peer)
        self.p2p_recv: Dict[int, int] = {}        # src -> seq (per peer)
        self.lock = threading.Lock()
        # (channel, seq, src) -> payload; channel "op" | "p2p"
        self.inbox: Dict[Tuple[str, int, int], Any] = {}
        self.cv = threading.Condition(self.lock)

    def deliver(self, chan: str, seq: int, src: int, payload: bytes):
        with self.cv:
            self.inbox[(chan, seq, src)] = payload
            self.cv.notify_all()

    def take(self, chan: str, seq: int, src: int, timeout: float) -> bytes:
        deadline = time.monotonic() + timeout
        with self.cv:
            while (chan, seq, src) not in self.inbox:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"collective {self.name}: no {chan} message "
                        f"seq={seq} from rank {src}")
                self.cv.wait(remaining)
            return self.inbox.pop((chan, seq, src))


def _worker():
    import ray_tpu

    return ray_tpu.api._worker()


def _deliver_push(group_name: str, chan: str, seq: int, src: int,
                  payload: bytes):
    """Called from the worker's RPC loop; never blocks — early messages
    are buffered and drained by init_collective_group."""
    with _groups_lock:
        g = _groups.get(group_name)
        if g is None:
            box = _undelivered.setdefault(group_name, [])
            box.append((chan, seq, src, payload, time.monotonic()))
            # bound the buffer; drop oldest orphans
            cutoff = time.monotonic() - 120.0
            _undelivered[group_name] = [
                m for m in box[-1000:] if m[4] > cutoff]
            return
    g.deliver(chan, seq, src, payload)


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default",
                          timeout: float = 60.0) -> None:
    """All members call this; rendezvous through the head KV.

    Stale entries from an earlier same-named gang are filtered by pinging
    every collected address and re-reading the KV for peers that fail —
    dead addresses never make it into the member table.
    """
    w = _worker()
    g = _Group(group_name, world_size, rank)
    with _groups_lock:
        _groups[group_name] = g
        early = _undelivered.pop(group_name, [])
    key = f"coll:{group_name}:{rank}"
    w.head.call("kv_put", key=key,
                value=pickle.dumps(tuple(w.address)), overwrite=True)
    deadline = time.monotonic() + timeout
    members: List[Optional[Tuple[str, int]]] = [None] * world_size
    members[rank] = tuple(w.address)
    while time.monotonic() < deadline:
        for r in [r for r in range(world_size) if members[r] is None]:
            reply = w.head.call("kv_get", key=f"coll:{group_name}:{r}")
            if reply.get("value") is not None:
                addr = pickle.loads(reply["value"])
                if _ping(w, addr):
                    members[r] = addr
                else:
                    # stale entry from a previous gang: drop and re-poll
                    w.head.call("kv_del", key=f"coll:{group_name}:{r}")
        if all(m is not None for m in members):
            g.members = members  # type: ignore[assignment]
            for chan, seq, src, payload, _ in early:
                g.deliver(chan, seq, src, payload)
            return
        time.sleep(0.02)
    raise TimeoutError(f"collective group {group_name}: only "
                       f"{sum(m is not None for m in members)}/{world_size} "
                       f"members joined")


def _ping(w, addr, timeout: float = 2.0) -> bool:
    async def _do():
        c = await w._aclient_worker(tuple(addr))
        return await c.call("ping", timeout=timeout)

    try:
        return bool(w._io.run(_do(), timeout=timeout + 5.0))
    except Exception:
        return False


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        w = _worker()
        for r in range(g.world_size):
            try:
                w.head.call("kv_del", key=f"coll:{group_name}:{r}")
            except Exception:
                pass


def _group(group_name: str) -> _Group:
    with _groups_lock:
        g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized here")
    return g


def _send_to(g: _Group, dst: int, seq: int, payload: bytes,
             chan: str = "op"):
    w = _worker()
    addr = g.members[dst]
    w._spawn(w._acoll_send(addr, g.name, chan, seq, g.rank, payload))


def send(data: np.ndarray, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send; sequenced per (src, dst) pair so sends to
    different peers cannot cross-match."""
    g = _group(group_name)
    with g.lock:
        g.p2p_send[dst_rank] = g.p2p_send.get(dst_rank, 0) + 1
        seq = g.p2p_send[dst_rank]
    _send_to(g, dst_rank, seq, pickle.dumps(np.asarray(data)), chan="p2p")


def recv(src_rank: int, group_name: str = "default",
         timeout: float = 60.0) -> np.ndarray:
    g = _group(group_name)
    with g.lock:
        g.p2p_recv[src_rank] = g.p2p_recv.get(src_rank, 0) + 1
        seq = g.p2p_recv[src_rank]
    return pickle.loads(g.take("p2p", seq, src_rank, timeout))


def _op_seq(g: _Group) -> int:
    with g.lock:
        g.seq += 1
        return g.seq


def allgather(data: np.ndarray, group_name: str = "default",
              timeout: float = 60.0) -> List[np.ndarray]:
    """Every rank returns [data_0, ..., data_{n-1}]."""
    g = _group(group_name)
    seq = _op_seq(g)
    arr = np.asarray(data)
    if g.rank == 0:
        parts: List[Any] = [arr] + [None] * (g.world_size - 1)
        for src in range(1, g.world_size):
            parts[src] = pickle.loads(g.take("op", seq, src, timeout))
        blob = pickle.dumps(parts)
        for dst in range(1, g.world_size):
            _send_to(g, dst, seq + 1, blob)
        with g.lock:
            g.seq += 1  # account for the broadcast step
        return parts
    _send_to(g, 0, seq, pickle.dumps(arr))
    out = pickle.loads(g.take("op", seq + 1, 0, timeout))
    with g.lock:
        g.seq += 1
    return out


_REDUCERS = {
    "sum": lambda parts: np.sum(parts, axis=0),
    "prod": lambda parts: np.prod(parts, axis=0),
    "max": lambda parts: np.max(parts, axis=0),
    "min": lambda parts: np.min(parts, axis=0),
}


def allreduce(data: np.ndarray, op: str = "sum",
              group_name: str = "default", timeout: float = 60.0) -> np.ndarray:
    parts = allgather(data, group_name, timeout)
    return _REDUCERS[op](np.stack([np.asarray(p) for p in parts]))


def reduce(data: np.ndarray, dst_rank: int = 0, op: str = "sum",
           group_name: str = "default", timeout: float = 60.0
           ) -> Optional[np.ndarray]:
    out = allreduce(data, op, group_name, timeout)
    g = _group(group_name)
    return out if g.rank == dst_rank else None


def broadcast(data: Optional[np.ndarray], src_rank: int = 0,
              group_name: str = "default", timeout: float = 60.0) -> np.ndarray:
    g = _group(group_name)
    seq = _op_seq(g)
    if g.rank == src_rank:
        blob = pickle.dumps(np.asarray(data))
        for dst in range(g.world_size):
            if dst != src_rank:
                _send_to(g, dst, seq, blob)
        return np.asarray(data)
    return pickle.loads(g.take("op", seq, src_rank, timeout))


def reducescatter(data: np.ndarray, op: str = "sum",
                  group_name: str = "default", timeout: float = 60.0
                  ) -> np.ndarray:
    """Each rank gets its 1/n slice (dim 0) of the reduction."""
    g = _group(group_name)
    total = allreduce(data, op, group_name, timeout)
    return np.array_split(total, g.world_size, axis=0)[g.rank]


def barrier(group_name: str = "default", timeout: float = 60.0) -> None:
    allgather(np.zeros(1), group_name, timeout)
