"""Drop-in ``multiprocessing.Pool`` running on the cluster.

Equivalent of the reference's ``ray.util.multiprocessing.Pool``
(reference: python/ray/util/multiprocessing/pool.py:1 — Pool with
apply/apply_async/map/map_async/starmap/imap/imap_unordered over actor
workers).  Workers are plain actors; chunking matches the stdlib's
heuristic so small-item workloads aren't dominated by per-task overhead.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional

from ray_tpu._private.errors import GetTimeoutError

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool


@ray_tpu.remote
class _PoolWorker:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_chunk(self, fn, chunk: List[tuple]) -> List[Any]:
        return [fn(*args) for args in chunk]

    def run_call(self, fn, args: tuple, kwds: dict) -> List[Any]:
        return [fn(*args, **kwds)]


class AsyncResult:
    """Matches ``multiprocessing.pool.AsyncResult``: get/wait/ready/successful."""

    def __init__(self, refs: List[Any], single: bool, unchunk: bool,
                 callback=None, error_callback=None):
        self._refs = refs
        self._single = single
        self._unchunk = unchunk
        self._callback = callback
        self._error_callback = error_callback
        self._value = None
        self._exc: Optional[BaseException] = None
        self._fetched = False
        self._lock = threading.Lock()
        if callback is not None or error_callback is not None:
            # stdlib fires callbacks when the result completes, not when
            # the caller asks for it
            threading.Thread(target=self._fetch, daemon=True,
                             name="mp-pool-callback").start()

    def _fetch(self):
        """Resolve and cache the final outcome; refs must be complete
        (or the caller accepts blocking until they are)."""
        with self._lock:
            if self._fetched:
                return
            try:
                chunks = ray_tpu.get(self._refs)
                out = list(itertools.chain.from_iterable(chunks)) \
                    if self._unchunk else chunks
                self._value = out[0] if self._single else out
                if self._callback is not None:
                    self._callback(self._value)
            except BaseException as exc:  # noqa: BLE001 — via get()
                self._exc = exc
                if self._error_callback is not None:
                    self._error_callback(exc)
            self._fetched = True

    def get(self, timeout: Optional[float] = None) -> Any:
        # wait OUTSIDE the cache lock: a timed-out get must not poison
        # the result, and must not block on the callback thread's fetch
        if not self._fetched and timeout is not None:
            ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                    timeout=timeout)
            if len(ready) < len(self._refs):
                raise GetTimeoutError("result not ready within timeout")
        self._fetch()
        if self._exc is not None:
            raise self._exc
        return self._value

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        self._fetch()
        return self._exc is None


def _chunk(iterable: Iterable, chunksize: int):
    it = iter(iterable)
    while True:
        block = list(itertools.islice(it, chunksize))
        if not block:
            return
        yield block


class Pool:
    """Process pool where each "process" is a cluster actor."""

    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=(), ray_remote_args: Optional[dict] = None):
        if processes is None:
            total = ray_tpu.cluster_resources().get("CPU", 1)
            processes = max(1, int(total))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        cls = _PoolWorker.options(**(ray_remote_args or {}))
        self._actors = [cls.remote(initializer, tuple(initargs))
                        for _ in range(processes)]
        self._closed = False
        self._next_apply = 0  # round-robins apply/apply_async

    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _default_chunksize(self, n_items: int) -> int:
        # stdlib heuristic: ~4 chunks per worker
        chunksize, extra = divmod(n_items, self._processes * 4)
        return chunksize + 1 if extra else max(1, chunksize)

    def _submit_chunks(self, fn, argtuples: List[tuple], chunksize):
        chunksize = chunksize or self._default_chunksize(len(argtuples))
        fn_ref = ray_tpu.put(fn)  # ship the function once, not per chunk
        n = len(self._actors)
        refs = []
        for i, block in enumerate(_chunk(argtuples, chunksize)):
            actor = self._actors[i % n]
            refs.append(actor.run_chunk.remote(fn_ref, block))
        return refs

    # -------------------------------------------------------------- apply

    def apply(self, fn: Callable, args=(), kwds=None) -> Any:
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args=(), kwds=None,
                    callback=None, error_callback=None) -> AsyncResult:
        self._check_running()
        actor = self._actors[self._next_apply % len(self._actors)]
        self._next_apply += 1
        ref = actor.run_call.remote(fn, tuple(args), kwds or {})
        return AsyncResult([ref], single=True, unchunk=True,
                           callback=callback, error_callback=error_callback)

    # ---------------------------------------------------------------- map

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check_running()
        args = [(x,) for x in iterable]
        refs = self._submit_chunks(fn, args, chunksize)
        return AsyncResult(refs, single=False, unchunk=True,
                           callback=callback, error_callback=error_callback)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        self._check_running()
        refs = self._submit_chunks(fn, [tuple(t) for t in iterable], chunksize)
        return AsyncResult(refs, single=False, unchunk=True).get()

    def starmap_async(self, fn: Callable, iterable: Iterable[tuple],
                      chunksize: Optional[int] = None) -> AsyncResult:
        self._check_running()
        refs = self._submit_chunks(fn, [tuple(t) for t in iterable], chunksize)
        return AsyncResult(refs, single=False, unchunk=True)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1):
        self._check_running()
        refs = self._submit_chunks(fn, [(x,) for x in iterable], chunksize)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1):
        self._check_running()
        refs = self._submit_chunks(fn, [(x,) for x in iterable], chunksize)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for ref in ready:
                yield from ray_tpu.get(ref)

    # ------------------------------------------------------------ lifecycle

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            ray_tpu.kill(a, no_restart=True)
        self._actors = []

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
