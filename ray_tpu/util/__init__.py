"""ray_tpu.util: placement groups, collectives, and cluster utilities."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          remove_placement_group)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy)

__all__ = ["ActorPool", "PlacementGroup", "placement_group",
           "remove_placement_group", "NodeAffinitySchedulingStrategy",
           "NodeLabelSchedulingStrategy"]
