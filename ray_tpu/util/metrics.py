"""User-facing metrics API.

Equivalent of the reference's application metrics
(reference: python/ray/util/metrics.py — Counter/Gauge/Histogram
recorded in any task/actor/driver and exported on the node's Prometheus
endpoint).  Metrics created in a worker are pushed to the node agent
and re-exported there with `worker_id` labels; the node agent's
endpoint is the one scrape target per node (see
_private/metrics.py and node_agent's metrics loop).
"""

from ray_tpu._private.metrics import (Counter, Gauge,  # noqa: F401
                                      Histogram, default_registry)
