"""Distributed FIFO queue backed by an actor.

Equivalent of the reference's ``ray.util.queue.Queue``
(reference: python/ray/util/queue.py:1 — actor-backed queue with
put/get/put_nowait/get_nowait/qsize/empty/full + batch variants and
Empty/Full mirroring the stdlib).  The reference hosts the buffer in an
asyncio actor; here the buffer lives in a threaded actor
(max_concurrency) and *blocking* semantics are driven client-side with
short bounded waits so an abandoned caller can never wedge an actor
thread forever.
"""

from __future__ import annotations

import queue as _stdlib_queue
import time
from typing import Any, List, Optional

import ray_tpu

Empty = _stdlib_queue.Empty
Full = _stdlib_queue.Full

_SLICE = 2.0  # max seconds an actor thread blocks per call


@ray_tpu.remote(max_concurrency=64)
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q = _stdlib_queue.Queue(maxsize=maxsize)

    def put(self, item, timeout: float) -> bool:
        try:
            self._q.put(item, block=True, timeout=min(timeout, _SLICE))
            return True
        except Full:
            return False

    def get(self, timeout: float):
        try:
            return True, self._q.get(block=True, timeout=min(timeout, _SLICE))
        except Empty:
            return False, None

    def put_nowait_batch(self, items: List[Any]) -> bool:
        """All-or-nothing: atomically accepts the whole batch or none."""
        q = self._q
        with q.not_full:  # the Condition shares q.mutex
            if q.maxsize > 0 and len(q.queue) + len(items) > q.maxsize:
                return False
            q.queue.extend(items)
            q.unfinished_tasks += len(items)
            q.not_empty.notify(len(items))
            return True

    def get_nowait_batch(self, num_items: int,
                         allow_partial: bool) -> Optional[List[Any]]:
        """Atomically drains num_items (or up to that many when
        allow_partial).  None = not enough items; nothing was drained."""
        q = self._q
        with q.not_empty:
            avail = len(q.queue)
            if avail < num_items and not allow_partial:
                return None
            take = min(num_items, avail)
            out = [q.queue.popleft() for _ in range(take)]
            q.not_full.notify(take)
            return out

    def qsize(self) -> int:
        return self._q.qsize()

    def maxsize(self) -> int:
        return self._q.maxsize


class Queue:
    """A first-in-first-out queue usable from any worker in the cluster."""

    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**(actor_options or {})).remote(maxsize)
        # fail fast if the actor could not be placed
        ray_tpu.get(self.actor.maxsize.remote(), timeout=60)

    # -------------------------------------------------------------- blocking

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            return self.put_nowait(item)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            wait = _SLICE if deadline is None \
                else max(0.0, deadline - time.monotonic())
            if ray_tpu.get(self.actor.put.remote(item, max(wait, 0.01)),
                           timeout=60):
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise Full

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            return self.get_nowait()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            wait = _SLICE if deadline is None \
                else max(0.0, deadline - time.monotonic())
            ok, item = ray_tpu.get(self.actor.get.remote(max(wait, 0.01)),
                                   timeout=60)
            if ok:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty

    # ----------------------------------------------------------- nonblocking

    def put_nowait(self, item: Any) -> None:
        if not ray_tpu.get(self.actor.put_nowait_batch.remote([item]),
                           timeout=60):
            raise Full

    def get_nowait(self) -> Any:
        out = ray_tpu.get(self.actor.get_nowait_batch.remote(1, False),
                          timeout=60)
        if out is None:
            raise Empty
        return out[0]

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items)),
                           timeout=60):
            raise Full(f"batch of {len(items)} does not fit")

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        out = ray_tpu.get(self.actor.get_nowait_batch.remote(num_items, False),
                          timeout=60)
        if out is None:
            raise Empty(f"fewer than {num_items} items available")
        return out

    # ------------------------------------------------------------ inspection

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote(), timeout=60)

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def shutdown(self, force: bool = False) -> None:
        """Terminate the backing actor; pending items are lost."""
        ray_tpu.kill(self.actor, no_restart=True)
