"""Actor pool: round-robins work over a fixed set of actor handles.

Equivalent of the reference's ``ray.util.ActorPool``
(reference: python/ray/util/actor_pool.py:1 — submit/get_next/
get_next_unordered/map/map_unordered/has_next/has_free/push/pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, List, TypeVar

import ray_tpu

V = TypeVar("V")


class ActorPool:
    """Utility for processing a stream of work items over a set of actors.

    ``fn`` passed to submit/map receives ``(actor_handle, value)`` and must
    return an ObjectRef, e.g. ``pool.submit(lambda a, v: a.work.remote(v), v)``.
    """

    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool requires at least one actor")
        self._in_flight: Dict[Any, tuple] = {}   # ref -> (actor, index)
        self._index_to_ref: Dict[int, Any] = {}  # submitted, not yet claimed
        self._done: Dict[int, Any] = {}          # completed, actor recycled
        self._pending: List[tuple] = []          # (fn, value) behind busy actors
        self._next_task_index = 0
        self._next_return_index = 0

    # ------------------------------------------------------------ submission

    def submit(self, fn: Callable[[Any, V], Any], value: V) -> None:
        """Schedule fn(actor, value) on an idle actor, or queue it."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._in_flight[ref] = (actor, self._next_task_index)
            self._index_to_ref[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending.append((fn, value))

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending

    def has_next(self) -> bool:
        return bool(self._in_flight) or bool(self._pending) or bool(self._done)

    # --------------------------------------------------------------- results

    def _recycle(self, ref) -> None:
        """Mark an in-flight ref completed; put its actor back to work."""
        actor, idx = self._in_flight.pop(ref)
        self._index_to_ref.pop(idx, None)
        self._done[idx] = ref
        self._idle.append(actor)
        if self._pending:
            fn, value = self._pending.pop(0)
            self.submit(fn, value)

    def _drain_one(self, timeout: float | None) -> None:
        """Block until any in-flight ref completes and recycle its actor."""
        if not self._in_flight:
            raise RuntimeError("ActorPool deadlock: queued work but no actors")
        ready, _ = ray_tpu.wait(
            list(self._in_flight), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("ActorPool wait timed out")
        self._recycle(ready[0])

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in submission order (blocks)."""
        if not self.has_next():
            raise StopIteration("no results pending")
        idx = self._next_return_index
        while idx not in self._done:
            ref = self._index_to_ref.get(idx)
            if ref is not None:
                # wait on the specific future we must return next
                ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
                if not ready:
                    raise TimeoutError("get_next timed out")
                self._recycle(ref)
            else:
                # still queued behind busy actors: free a slot first
                self._drain_one(timeout)
        self._next_return_index += 1
        return ray_tpu.get(self._done.pop(idx))

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Next result in completion order (blocks)."""
        if not self.has_next():
            raise StopIteration("no results pending")
        if not self._done:
            self._drain_one(timeout)
        idx = next(iter(self._done))
        self._next_return_index = max(self._next_return_index, idx + 1)
        return ray_tpu.get(self._done.pop(idx))

    # ------------------------------------------------------------------ maps

    def map(self, fn: Callable[[Any, V], Any],
            values: Iterable[V]) -> Iterator[Any]:
        """Apply fn over values; yields results in submission order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, V], Any],
                      values: Iterable[V]) -> Iterator[Any]:
        """Apply fn over values; yields results as they complete."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ------------------------------------------------------------ membership

    def push(self, actor: Any) -> None:
        """Add an idle actor to the pool."""
        busy = {a for a, _ in self._in_flight.values()}
        if actor in self._idle or actor in busy:
            raise ValueError("actor already in pool")
        self._idle.append(actor)
        if self._pending:
            fn, value = self._pending.pop(0)
            self.submit(fn, value)

    def pop_idle(self) -> Any | None:
        """Remove and return an idle actor, or None if all are busy."""
        if self._idle:
            return self._idle.pop()
        return None
