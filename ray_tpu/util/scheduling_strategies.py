"""Scheduling strategies for tasks and actors.

Equivalent of the reference's strategy objects
(reference: python/ray/util/scheduling_strategies.py —
NodeAffinitySchedulingStrategy :1, NodeLabelSchedulingStrategy, and the
"SPREAD"/"DEFAULT" string strategies).
"""

from __future__ import annotations

from typing import Dict, Optional, Union


class NodeAffinitySchedulingStrategy:
    """Pin a task/actor to one node.  ``soft=False`` fails scheduling if
    the node cannot take it; ``soft=True`` falls back to the default
    policy."""

    def __init__(self, node_id: str, soft: bool = False):
        if not node_id:
            raise ValueError("node_id is required")
        self.node_id = node_id
        self.soft = soft

    def to_wire(self) -> Dict[str, object]:
        return {"type": "node_affinity", "node_id": self.node_id,
                "soft": self.soft}


class NodeLabelSchedulingStrategy:
    """Restrict placement to nodes whose labels match ``hard`` exactly."""

    def __init__(self, hard: Optional[Dict[str, str]] = None):
        self.hard = dict(hard or {})

    def to_wire(self) -> Dict[str, object]:
        return {"type": "node_label", "hard": self.hard}


SchedulingStrategyT = Union[str, NodeAffinitySchedulingStrategy,
                            NodeLabelSchedulingStrategy, None]


def strategy_to_wire(strategy: SchedulingStrategyT) -> Dict[str, object]:
    if strategy is None or strategy == "DEFAULT":
        return {}
    if strategy == "SPREAD":
        return {"type": "spread"}
    if isinstance(strategy, (NodeAffinitySchedulingStrategy,
                             NodeLabelSchedulingStrategy)):
        return strategy.to_wire()
    raise ValueError(f"unknown scheduling strategy: {strategy!r}")
