"""State API: cluster introspection for humans and tools.

Equivalent of the reference's state API
(reference: python/ray/util/state/api.py — list_tasks/list_actors/
list_objects/list_nodes backed by the state head aggregating GCS
tables and per-raylet GetTasksInfo/GetObjectsInfo;
src/ray/gcs/gcs_server/gcs_task_manager.h for the task-event store).
`timeline()` renders the task-event store as a Chrome trace, the
equivalent of `ray.timeline()`.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def _head():
    import ray_tpu

    return ray_tpu.api._worker().head


def list_tasks(state: str = "", name: str = "",
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Task records merged from worker-flushed state transitions.
    Filters: state in SUBMITTED/RUNNING/FINISHED/FAILED, task name."""
    return _head().call("list_tasks", state=state, name=name,
                        limit=limit)["tasks"]


def list_actors() -> List[Dict[str, Any]]:
    return _head().call("list_actors")["actors"]


def list_nodes() -> List[Dict[str, Any]]:
    table = _head().call("node_table")
    return list(table.values())


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    """Plasma object summaries aggregated across every node's store."""
    return _head().call("list_objects", limit=limit)["objects"]


def list_placement_groups() -> List[Dict[str, Any]]:
    return _head().call("list_placement_groups")["placement_groups"]


def summarize_tasks() -> Dict[str, Dict[str, Any]]:
    """Per-function task aggregates off the head's task-event store
    (reference: `ray summary tasks`): for each task/method name, state
    counts plus queued (submitted→leased) and running (running→done)
    p50/p99/mean percentiles — ``{name: {"kind", "states",
    "queued": {p50_ms, p99_ms, ...} | None, "running": ...}}``."""
    return _head().call("cluster_summary")["tasks"]


def summarize_actors() -> Dict[str, Any]:
    """Actor rollup (reference: `ray summary actors`): counts by state
    plus per-method call counts from the task-event store."""
    return _head().call("cluster_summary")["actors"]


def summarize_objects() -> Dict[str, Any]:
    """Cluster object-store rollup from the per-node heartbeat byte
    breakdowns (reference: `ray summary objects`): totals for arena,
    pinned, spilled and channel bytes plus the per-node breakdowns."""
    return _head().call("cluster_summary")["objects"]


def memory_summary(top_n: int = 0) -> Dict[str, Any]:
    """The joined cluster memory view behind `rtpu memory` (reference:
    `ray memory`): per-node byte breakdowns, top-N objects by size with
    owner + creation call-site, per-owner ref counts, and the `leaks`
    tripwire section (dead-owner pins, borrowed refs past TTL, orphaned
    channel slots)."""
    return _head().call("memory_view", top_n=top_n, timeout=60)


def task_timeline_events(records) -> List[Dict[str, Any]]:
    """Chrome-trace events from merged task records (shared by
    `timeline()` and the head's /api/timeline):

    - one ``ph:"X"`` duration slice per executed task (as before);
    - ``ph:"s"``/``ph:"f"`` flow events tying the submit point (on the
      submitter's track) to the execution slice (on the executor's
      track), so Perfetto draws submit→execute causality arrows;
    - one ``ph:"i"`` instant event for tasks that FAILED without ever
      reaching ``running`` (cancelled/errored while queued) — previously
      these were silently dropped from the trace.
    """
    events: List[Dict[str, Any]] = []
    for t in records:
        start = t.get("running_ts")
        end = t.get("finished_ts") or t.get("failed_ts")
        name = t.get("name") or t.get("task_id", "")[:8]
        if start is not None and end is not None:
            events.append({
                "name": name,
                "cat": t.get("kind", "task"),
                "ph": "X",
                "ts": int(start * 1e6),
                "dur": max(1, int((end - start) * 1e6)),
                "pid": t.get("node_id", "")[:8],
                "tid": t.get("worker_id", "")[:8],
                "args": {"task_id": t.get("task_id"),
                         "state": t.get("state")},
            })
            sub = t.get("submitted_ts")
            if sub is not None:
                fid = t.get("task_id", "")[:16]
                events.append({
                    "name": "submit", "cat": "task_flow", "ph": "s",
                    "id": fid, "ts": int(sub * 1e6),
                    "pid": (t.get("caller_node_id")
                            or t.get("node_id", ""))[:8],
                    "tid": (t.get("caller_worker_id")
                            or t.get("worker_id", ""))[:8],
                })
                events.append({
                    "name": "submit", "cat": "task_flow", "ph": "f",
                    "bt": "e", "id": fid,
                    "ts": int(start * 1e6),
                    "pid": t.get("node_id", "")[:8],
                    "tid": t.get("worker_id", "")[:8],
                })
        elif end is not None:
            # never ran: instant event at the failure point so
            # queue-time failures stay visible in the trace
            events.append({
                "name": name,
                "cat": t.get("kind", "task"),
                "ph": "i", "s": "p",
                "ts": int(end * 1e6),
                "pid": (t.get("node_id")
                        or t.get("caller_node_id", ""))[:8],
                "tid": (t.get("worker_id")
                        or t.get("caller_worker_id", ""))[:8],
                "args": {"task_id": t.get("task_id"),
                         "state": t.get("state"),
                         "error": t.get("error", "")},
            })
    return events


def timeline(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-trace events (chrome://tracing / perfetto) from the task
    event store (reference: ray.timeline(), task profile events).
    Returns the event list; writes JSON to `path` if given."""
    events = task_timeline_events(list_tasks(limit=100_000))
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events


def list_traces(limit: int = 100) -> List[Dict[str, Any]]:
    """Summaries of recent traces from the head's trace store, newest
    first (reference: ray.util.tracing — exported spans, here queryable
    in-cluster)."""
    return _head().call("list_traces", limit=limit)["traces"]


def get_trace(trace_id: str) -> Dict[str, Any]:
    """One trace: every flushed span, sorted by start time.  Raises
    ValueError if the trace is unknown (not sampled, expired from the
    bounded store, or not flushed yet)."""
    reply = _head().call("get_trace", trace_id=trace_id)
    if not reply.get("found"):
        raise ValueError(f"no trace {trace_id!r} in the trace store")
    return reply["trace"]


def get_log(node_id: str = "", filename: str = "",
            tail: int = 1000) -> str:
    """Read a daemon/worker log from the session directory
    (reference: ray.util.state.get_log)."""
    import glob
    import os

    import ray_tpu

    w = ray_tpu.api._worker()
    session = getattr(w, "session_dir", None)
    if session is None:
        base = os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")
        sessions = sorted(glob.glob(os.path.join(base, "session_*")))
        if not sessions:
            return ""
        session = sessions[-1]
    logs = os.path.join(session, "logs")
    if filename:
        # an explicit filename must resolve exactly — silently falling
        # back to "latest log" here returned the WRONG file on typos
        target = os.path.join(logs, filename)
        if not os.path.exists(target):
            raise FileNotFoundError(
                f"no log file {filename!r} under {logs}")
    else:
        candidates = sorted(glob.glob(os.path.join(logs, "*.log")))
        if not candidates:
            return ""
        target = candidates[-1]
    with open(target, errors="replace") as f:
        lines = f.readlines()
    return "".join(lines[-tail:])
