"""State API: cluster introspection for humans and tools.

Equivalent of the reference's state API
(reference: python/ray/util/state/api.py — list_tasks/list_actors/
list_objects/list_nodes backed by the state head aggregating GCS
tables and per-raylet GetTasksInfo/GetObjectsInfo;
src/ray/gcs/gcs_server/gcs_task_manager.h for the task-event store).
`timeline()` renders the task-event store as a Chrome trace, the
equivalent of `ray.timeline()`.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def _head():
    import ray_tpu

    return ray_tpu.api._worker().head


def list_tasks(state: str = "", name: str = "",
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Task records merged from worker-flushed state transitions.
    Filters: state in SUBMITTED/RUNNING/FINISHED/FAILED, task name."""
    return _head().call("list_tasks", state=state, name=name,
                        limit=limit)["tasks"]


def list_actors() -> List[Dict[str, Any]]:
    return _head().call("list_actors")["actors"]


def list_nodes() -> List[Dict[str, Any]]:
    table = _head().call("node_table")
    return list(table.values())


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    """Plasma object summaries aggregated across every node's store."""
    return _head().call("list_objects", limit=limit)["objects"]


def list_placement_groups() -> List[Dict[str, Any]]:
    return _head().call("list_placement_groups")["placement_groups"]


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    """Counts by task name and state (reference: `ray summary tasks`)."""
    out: Dict[str, Dict[str, int]] = {}
    for t in list_tasks(limit=100_000):
        name = t.get("name", "?")
        state = t.get("state", "?")
        row = out.setdefault(name, {})
        row[state] = row.get(state, 0) + 1
    return out


def timeline(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-trace events (chrome://tracing / perfetto) from the task
    event store (reference: ray.timeline(), task profile events).
    Returns the event list; writes JSON to `path` if given."""
    events: List[Dict[str, Any]] = []
    for t in list_tasks(limit=100_000):
        start = t.get("running_ts")
        end = t.get("finished_ts") or t.get("failed_ts")
        if start is None or end is None:
            continue
        events.append({
            "name": t.get("name", t["task_id"][:8]),
            "cat": t.get("kind", "task"),
            "ph": "X",
            "ts": int(start * 1e6),
            "dur": max(1, int((end - start) * 1e6)),
            "pid": t.get("node_id", "")[:8],
            "tid": t.get("worker_id", "")[:8],
            "args": {"task_id": t["task_id"], "state": t.get("state")},
        })
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events


def get_log(node_id: str = "", filename: str = "",
            tail: int = 1000) -> str:
    """Read a daemon/worker log from the session directory
    (reference: ray.util.state.get_log)."""
    import glob
    import os

    import ray_tpu

    w = ray_tpu.api._worker()
    session = getattr(w, "session_dir", None)
    if session is None:
        base = os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")
        sessions = sorted(glob.glob(os.path.join(base, "session_*")))
        if not sessions:
            return ""
        session = sessions[-1]
    logs = os.path.join(session, "logs")
    target = os.path.join(logs, filename) if filename else None
    if target is None or not os.path.exists(target):
        candidates = sorted(glob.glob(os.path.join(logs, "*.log")))
        if not candidates:
            return ""
        target = candidates[-1]
    with open(target, errors="replace") as f:
        lines = f.readlines()
    return "".join(lines[-tail:])
