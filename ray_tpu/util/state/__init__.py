from ray_tpu.util.state.api import (get_log, list_actors,  # noqa: F401
                                    list_nodes, list_objects,
                                    list_placement_groups, list_tasks,
                                    summarize_tasks, timeline)
