from ray_tpu.util.state.api import (get_log, get_trace,  # noqa: F401
                                    list_actors, list_nodes, list_objects,
                                    list_placement_groups, list_tasks,
                                    list_traces, memory_summary,
                                    summarize_actors, summarize_objects,
                                    summarize_tasks, timeline)
