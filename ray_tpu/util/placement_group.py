"""Placement groups: gang-scheduled resource bundles.

Equivalent of the reference's placement group API
(reference: python/ray/util/placement_group.py:145 placement_group();
server side src/ray/gcs/gcs_server/gcs_placement_group_manager.h,
bundle policies src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h).

Reservation is all-or-nothing, which is what makes multi-host TPU slices
gang-schedulable: a slice's per-host bundles either all reserve or none
do (SURVEY §7.4 hard part 2).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.errors import RayError

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroupError(RayError):
    pass


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: Optional[List[Dict[str, float]]] = None,
                 info: Optional[Dict] = None):
        self.id = pg_id
        self._bundles = bundles or []
        # create-reply snapshot: when the head's inline scheduling pass
        # already committed the group, ready()/wait() answer from this
        # with no extra round trip (PG churn is a benchmarked hot path)
        self._created_info = info if (info or {}).get("state") == "CREATED" \
            else None

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self._bundles

    def _info(self, wait: bool = False, wait_s: Optional[float] = None) -> Dict:
        import ray_tpu

        w = ray_tpu.api._worker()
        return w.head.call("get_placement_group", pg_id=self.id, wait=wait,
                           wait_s=wait_s,
                           timeout=(wait_s or 30.0) + 30.0)

    def ready(self, timeout: Optional[float] = None) -> "PlacementGroup":
        """Block until every bundle is reserved (gang commit).

        Reference exposes ready() as an ObjectRef; blocking with a timeout
        is the ergonomic equivalent for this API.
        """
        if self._created_info is not None:
            # one-shot: the create reply proved CREATED for the first
            # ready()/wait(); later calls must re-poll — the group may
            # have gone back to PENDING on a node death or been removed,
            # and a cached success would lie about it.  (A bundle lost
            # in the tiny create→first-wait window is still recovered by
            # the lease path's "bundle not reserved" refresh-and-retry.)
            self._created_info = None
            return self
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise TimeoutError("placement group not ready in time")
            info = self._info(wait=True,
                              wait_s=min(remaining or 25.0, 25.0))
            if info["state"] == "CREATED":
                return self
            if info["state"] == "REMOVED":
                raise PlacementGroupError("placement group was removed")
            if info.get("failure"):
                raise PlacementGroupError(info["failure"])

    def wait(self, timeout: float = 30.0) -> bool:
        try:
            self.ready(timeout=timeout)
            return True
        except (TimeoutError, PlacementGroupError):
            return False

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    import ray_tpu

    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    from ray_tpu._private.ids import PlacementGroupID

    w = ray_tpu.api._worker()
    # client-generated id makes the create idempotent across retries
    reply = w.head.call("create_placement_group", bundles=list(bundles),
                        strategy=strategy, name=name,
                        pg_id=PlacementGroupID.from_random().hex())
    return PlacementGroup(reply["pg_id"], list(bundles),
                          info=reply.get("info"))


def placement_group_table() -> List[Dict]:
    """All placement groups with states and placements
    (reference: python/ray/util/placement_group.py placement_group_table)."""
    import ray_tpu

    w = ray_tpu.api._worker()
    return w.head.call("list_placement_groups")["placement_groups"]


def remove_placement_group(pg: PlacementGroup) -> None:
    import ray_tpu

    w = ray_tpu.api._worker()
    w.head.call("remove_placement_group", pg_id=pg.id)


def tpu_slice_bundles(num_hosts: int, chips_per_host: int = 4,
                      accelerator_type: str = "",
                      cpus_per_host: float = 1.0) -> List[Dict[str, float]]:
    """Bundles for an ICI-connected TPU slice: one bundle per host, gang
    scheduled STRICT_SPREAD so each lands on a distinct TPU host
    (reference: accelerators/tpu.py:335-398 TPU-{type}-head trick).
    Each bundle carries CPU for the host-side worker process — tasks
    default to 1 CPU and must fit their bundle."""
    bundles: List[Dict[str, float]] = []
    for host in range(num_hosts):
        b: Dict[str, float] = {"TPU": float(chips_per_host),
                               "CPU": float(cpus_per_host)}
        if accelerator_type and host == 0:
            b[f"TPU-{accelerator_type}-head"] = 1.0
        bundles.append(b)
    return bundles
