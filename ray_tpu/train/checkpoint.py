"""Checkpoint helpers: orbax-backed sharded save/restore + top-k retention.

Equivalent of the reference's Checkpoint/CheckpointManager
(reference: python/ray/train/_checkpoint.py — a checkpoint is a
directory; _internal/checkpoint_manager.py — top-k retention by metric).
TPU slant: orbax writes each jax.Array shard from the host that owns it,
so saving a GSPMD-sharded train state from a multi-host mesh needs no
gather; restore honors a target tree's shardings.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional


def save_checkpoint(path: str, state: Any) -> str:
    """Write a pytree of (possibly sharded) jax arrays to `path`."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state)
    return path


def restore_checkpoint(path: str, target: Any = None) -> Any:
    """Read a pytree back; with `target`, restores to its dtypes/shapes
    and (for jax.Array leaves) its shardings — the multi-host path."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        if target is None:
            return ckptr.restore(path)
        return ckptr.restore(
            path, args=ocp.args.PyTreeRestore(
                item=target,
                restore_args=ocp.checkpoint_utils.construct_restore_args(target)))


class CheckpointManager:
    """Top-k checkpoint retention by metric
    (reference: train/_internal/checkpoint_manager.py)."""

    def __init__(self, directory: str, *, num_to_keep: int = 2,
                 metric: Optional[str] = None, mode: str = "min",
                 storage: Optional["StorageContext"] = None):
        assert mode in ("min", "max")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.num_to_keep = num_to_keep
        self.metric = metric
        self.mode = mode
        # optional remote persistence (reference: StorageContext —
        # checkpoints upload after local save, restore works from any
        # host that can reach the storage path)
        self.storage = storage
        self._entries: List[Dict[str, Any]] = []
        self._counter = 0
        self._load_index()
        if not self._entries and storage is not None:
            self._load_storage_index()

    def _index_path(self) -> str:
        return os.path.join(self.directory, "index.json")

    def _load_index(self):
        try:
            with open(self._index_path()) as f:
                data = json.load(f)
            self._entries = data["entries"]
            self._counter = data["counter"]
        except (OSError, ValueError, KeyError):
            pass

    def _save_index(self):
        with open(self._index_path(), "w") as f:
            json.dump({"entries": self._entries, "counter": self._counter}, f)

    def _load_storage_index(self):
        text = self.storage.read_text("checkpoints/index.json")
        if not text:
            return
        try:
            data = json.loads(text)
            self._entries = data["entries"]
            self._counter = data["counter"]
        except (ValueError, KeyError):
            pass

    def save(self, state: Any, metrics: Optional[Dict[str, Any]] = None) -> str:
        self._counter += 1
        name = f"ckpt_{self._counter:06d}"
        path = os.path.join(self.directory, name)
        save_checkpoint(path, state)
        entry: Dict[str, Any] = {"path": path, "metrics": metrics or {}}
        if self.storage is not None:
            # any storage path (NFS dir, memory://, s3://) gets the copy;
            # a local path identical to `path` is a no-op
            entry["uri"] = self.storage.persist_dir(
                path, f"checkpoints/{name}")
        self._entries.append(entry)
        self._evict()
        self._save_index()
        if self.storage is not None:
            self.storage.write_text(
                "checkpoints/index.json",
                json.dumps({"entries": self._entries,
                            "counter": self._counter}))
        return path

    def fetch(self, entry_path: str) -> str:
        """Local path for a checkpoint, downloading from storage when
        the local copy is absent (fresh host after a failover)."""
        if os.path.exists(entry_path):
            return entry_path
        entry = next((e for e in self._entries
                      if e["path"] == entry_path), None)
        if entry is None or "uri" not in entry or self.storage is None:
            return entry_path
        local = os.path.join(self.directory, os.path.basename(entry_path))
        return self.storage.fetch_dir(entry["uri"], local)

    def _score(self, entry) -> float:
        if self.metric is None:
            return 0.0
        v = entry["metrics"].get(self.metric)
        if v is None:  # metric-less checkpoints always rank worst
            return float("-inf") if self.mode == "max" else float("inf")
        return float(v)

    def _evict(self):
        if len(self._entries) <= self.num_to_keep:
            return
        # keep the k best by metric; metric-less -> most recent k
        # (reference: checkpoint_manager.py default recency retention);
        # the latest checkpoint is always kept for resume
        latest = self._entries[-1]
        if self.metric is None:
            keep = self._entries[-self.num_to_keep:]
        else:
            ranked = sorted(
                self._entries[:-1],
                key=self._score, reverse=(self.mode == "max"))
            keep = ranked[:self.num_to_keep - 1] + [latest]
        for entry in self._entries:
            if entry not in keep:
                shutil.rmtree(entry["path"], ignore_errors=True)
                if "uri" in entry and self.storage is not None:
                    try:  # evicted checkpoints leave storage too
                        if self.storage.fs is None:
                            if entry["uri"] != entry["path"]:
                                shutil.rmtree(entry["uri"],
                                              ignore_errors=True)
                        else:
                            self.storage.fs.rm(
                                entry["uri"].split("://", 1)[1],
                                recursive=True)
                    except Exception:
                        pass
        self._entries = [e for e in self._entries if e in keep]

    def best_checkpoint(self) -> Optional[str]:
        if not self._entries:
            return None
        if self.metric is None:
            return self._entries[-1]["path"]
        ranked = sorted(self._entries, key=self._score,
                        reverse=(self.mode == "max"))
        return ranked[0]["path"]

    def latest_checkpoint(self) -> Optional[str]:
        return self._entries[-1]["path"] if self._entries else None


class ActorStateCheckpoint:
    """Pickled-blob snapshots for stateful actor restarts.

    Rides the same StorageContext layer CheckpointManager persists
    through, but stores one cloudpickle blob per snapshot instead of an
    orbax pytree directory — actor ``__rt_save__`` state is arbitrary
    Python (counters, KV maps, optimizer trees), and a restart must be
    able to read it from ANY node that can reach the storage path.

    Layout under the storage root (default <session_dir>/actor_state):
      <actor_id>/index.json       {"counter": N, "entries": [rel, ...]}
      <actor_id>/snap_000001.pkl  the snapshots (last `keep` retained)

    The blob is written BEFORE the index (both atomically), so a crash
    between the two leaves the previous index pointing at intact data —
    a restart never reads a torn snapshot.
    """

    INDEX = "index.json"

    def __init__(self, storage: "StorageContext", actor_id: str,
                 keep: int = 2):
        self.storage = storage
        self.prefix = actor_id
        self.keep = max(1, keep)
        self._counter = 0
        self._entries: List[str] = []
        self._load_index()

    def _rel(self, name: str) -> str:
        import posixpath

        return posixpath.join(self.prefix, name)

    def _load_index(self) -> None:
        text = self.storage.read_text(self._rel(self.INDEX))
        if not text:
            return
        try:
            data = json.loads(text)
            self._counter = int(data["counter"])
            self._entries = list(data["entries"])
        except (ValueError, KeyError):
            pass  # corrupt index: treat as no snapshots

    def save(self, state: Any) -> str:
        import cloudpickle

        self._counter += 1
        name = f"snap_{self._counter:06d}.pkl"
        self.storage.write_bytes(self._rel(name), cloudpickle.dumps(state))
        self._entries.append(name)
        evicted, self._entries = (self._entries[:-self.keep],
                                  self._entries[-self.keep:])
        self.storage.write_text(
            self._rel(self.INDEX),
            json.dumps({"counter": self._counter,
                        "entries": self._entries}))
        for old in evicted:
            self.storage.remove(self._rel(old))
        return name

    def entry_names(self) -> List[str]:
        """Retained snapshot names, oldest first."""
        return list(self._entries)

    def load_entry(self, name: str) -> Any:
        """One specific retained snapshot's state, or None when the blob
        is missing/unreadable.  The pipeline's restart protocol uses this
        to roll every stage back to a COMMON step: after a mid-step
        death, stages can hold different latest snapshots (the drained
        last stage saves step t+1 before upstream stages finish it), so
        recovery enumerates entries and restores the newest step present
        on every stage rather than each stage's own latest."""
        import cloudpickle

        if name not in self._entries:
            return None
        blob = self.storage.read_bytes(self._rel(name))
        if blob is None:
            return None
        try:
            return cloudpickle.loads(blob)
        except Exception:
            return None

    def load_latest(self) -> Any:
        """The newest readable snapshot's state, or None when the actor
        has never saved (falling back through older snapshots if the
        newest blob is missing/unreadable)."""
        import cloudpickle

        for name in reversed(self._entries):
            blob = self.storage.read_bytes(self._rel(name))
            if blob is None:
                continue
            try:
                return cloudpickle.loads(blob)
            except Exception:
                continue
        return None

    def has_snapshot(self) -> bool:
        return bool(self._entries)

    def delete(self) -> None:
        for name in list(self._entries):
            self.storage.remove(self._rel(name))
        self.storage.remove(self._rel(self.INDEX))
        self._entries = []
