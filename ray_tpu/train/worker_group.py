"""WorkerGroup: a gang of actors executing SPMD work.

Equivalent of the reference's WorkerGroup
(reference: python/ray/train/_internal/worker_group.py) plus the rank
bookkeeping from BackendExecutor
(reference: _internal/backend_executor.py:347
_create_rank_world_size_mappings).
"""

from __future__ import annotations

import os
import socket
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import cloudpickle


def _node_ip() -> str:
    """This host's outbound IP (reference: ray._private.services
    get_node_ip_address — UDP-connect trick, no packets sent)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


class TrainWorker:
    """Actor hosting one rank of the SPMD gang. The user's train loop
    runs in a thread so poll() stays responsive (actor methods execute
    serially)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self._session = None
        self._thread: Optional[threading.Thread] = None

    # ---- gang metadata -----------------------------------------------------

    def node_info(self) -> Dict[str, Any]:
        return {"hostname": socket.gethostname(), "pid": os.getpid(),
                "ip": _node_ip()}

    def free_port(self) -> int:
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def setup_env(self, env: Dict[str, str]) -> bool:
        os.environ.update(env)
        return True

    def init_jax_distributed(self, coordinator: str, num_processes: int,
                             process_id: int) -> int:
        """Multi-host rendezvous (equivalent of torch process-group setup,
        reference: train/torch/config.py:64)."""
        import jax

        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return jax.device_count()

    # ---- training ----------------------------------------------------------

    def run_async(self, fn_blob: bytes, config: Optional[Dict[str, Any]],
                  checkpoint: Optional[str] = None,
                  experiment_name: str = "", trial_dir: str = "",
                  datasets: Optional[Dict[str, Any]] = None) -> bool:
        from ray_tpu.train.session import TrainContext, _Session, _set_session

        fn = cloudpickle.loads(fn_blob)
        ctx = TrainContext(rank=self.rank, world_size=self.world_size,
                           local_rank=0, experiment_name=experiment_name,
                           trial_dir=trial_dir)
        session = _Session(ctx, checkpoint_to_restore=checkpoint,
                           datasets=datasets)
        self._session = session

        def target():
            from ray_tpu.train.session import StopTrial

            _set_session(session)
            try:
                if config is not None:
                    session.final = fn(config)
                else:
                    session.final = fn()
            except StopTrial:
                pass  # controller-requested early stop: clean exit
            except BaseException as e:  # reported via poll()
                session.error = e
                session.reports.append(
                    {"metrics": {"_error": traceback.format_exc()},
                     "checkpoint": None})
            finally:
                session.finished.set()
                _set_session(None)

        self._thread = threading.Thread(target=target, name="rt-train", daemon=True)
        self._thread.start()
        return True

    def poll(self) -> Dict[str, Any]:
        s = self._session
        if s is None:
            return {"done": True, "reports": [], "error": None, "final": None}
        done = s.finished.is_set()
        err = None
        if done and s.error is not None:
            try:
                err = cloudpickle.dumps(s.error)
            except Exception:
                err = cloudpickle.dumps(RuntimeError(str(s.error)))
        return {"done": done, "reports": s.drain(), "error": err,
                "final": s.final if done and s.error is None else None}

    def request_stop(self) -> bool:
        """Ask the running loop to stop at its next report()."""
        if self._session is not None:
            self._session.stop_requested.set()
        return True

    def shutdown_worker(self) -> bool:
        return True


class WorkerGroup:
    """Driver-side handle to the actor gang."""

    def __init__(self, num_workers: int,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 worker_cls: Any = None):
        import ray_tpu

        cls = ray_tpu.remote(worker_cls or TrainWorker)
        if resources_per_worker:
            cls = cls.options(resources=dict(resources_per_worker))
        self.num_workers = num_workers
        self.workers = [cls.remote(rank, num_workers)
                        for rank in range(num_workers)]

    def execute(self, method: str, *args, timeout: Optional[float] = 120.0,
                **kwargs) -> List[Any]:
        """Call a method on every worker, gather results (barrier)."""
        import ray_tpu

        refs = [getattr(w, method).remote(*args, **kwargs) for w in self.workers]
        return ray_tpu.get(refs, timeout=timeout)

    def execute_single(self, rank: int, method: str, *args,
                       timeout: Optional[float] = 120.0, **kwargs) -> Any:
        import ray_tpu

        ref = getattr(self.workers[rank], method).remote(*args, **kwargs)
        return ray_tpu.get(ref, timeout=timeout)

    def shutdown(self):
        import ray_tpu

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
