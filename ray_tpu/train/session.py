"""Per-worker training session: rank context + report channel.

Equivalent of the reference's _TrainSession
(reference: python/ray/train/_internal/session.py:109 — report :401,
public train.report :661, context accessors python/ray/train/context.py).
The user's train loop runs in a thread inside the TrainWorker actor;
`report(metrics, checkpoint=...)` enqueues results that the driver-side
trainer drains via actor polls.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class TrainContext:
    rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: str = ""

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank

    def get_local_rank(self) -> int:
        return self.local_rank


class StopTrial(Exception):
    """Raised inside report() when the controller requested a stop
    (reference: function_trainable.py StopCallback semantics)."""


class _Session:
    def __init__(self, ctx: TrainContext,
                 checkpoint_to_restore: Optional[str] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self.ctx = ctx
        self.lock = threading.Lock()
        self.reports: List[Dict[str, Any]] = []
        self.checkpoint_to_restore = checkpoint_to_restore
        self.datasets = datasets or {}
        self.finished = threading.Event()
        self.stop_requested = threading.Event()
        self.error: Optional[BaseException] = None
        self.final: Any = None

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[str]):
        if self.stop_requested.is_set():
            raise StopTrial()
        with self.lock:
            self.reports.append({"metrics": dict(metrics),
                                 "checkpoint": checkpoint})

    def drain(self) -> List[Dict[str, Any]]:
        with self.lock:
            out, self.reports = self.reports, []
            return out


_current: Optional[_Session] = None


def _set_session(s: Optional[_Session]):
    global _current
    _current = s


def _get_session() -> _Session:
    if _current is None:
        raise RuntimeError(
            "No training session: report()/get_context() must be called "
            "from inside a train loop launched by JaxTrainer")
    return _current


def get_context() -> TrainContext:
    return _get_session().ctx


def report(metrics: Dict[str, Any], checkpoint: Optional[str] = None) -> None:
    """Report metrics (and optionally a checkpoint directory) from a
    training worker (reference: train.report, session.py:661)."""
    _get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[str]:
    """Checkpoint directory to restore from, when resuming."""
    return _get_session().checkpoint_to_restore


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed to JaxTrainer(datasets=...)
    (reference: ray.train.get_dataset_shard)."""
    ds = _get_session().datasets.get(name)
    if ds is None:
        raise KeyError(f"no dataset shard named {name!r} for this worker")
    return ds
