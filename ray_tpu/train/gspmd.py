"""GSPMD training-step construction for the flagship model.

This is the TPU-native equivalent of the reference's prepare_model
DDP/FSDP wrapping (reference: python/ray/train/torch/train_loop_utils.py
:158-186): instead of wrapping a module, we place parameters with
PartitionSpecs on a named mesh and jit one train step; XLA inserts the
all-gathers/reduce-scatters (fsdp), all-reduces (dp) and collective
matmuls (tp) over ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple


def build_llama_train_state(cfg, mesh, rng_seed: int = 0,
                            learning_rate: float = 3e-4,
                            batch_size: int = 8, seq_len: int = 128,
                            attention_kernel: Optional[Callable] = None):
    """Init sharded (params, opt_state) and a jitted train step.

    Returns (params, opt_state, step_fn, model) where
    step_fn(params, opt_state, tokens) -> (params, opt_state, loss).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import (LlamaModel, causal_lm_loss,
                                      llama_param_rules)
    from ray_tpu.parallel.mesh import shard_batch, shard_params

    if attention_kernel is None and mesh.shape.get("sp", 1) > 1:
        # sequence-parallel mesh: ring attention rotates KV over ICI
        from ray_tpu.ops.ring_attention import make_ring_attention

        attention_kernel = make_ring_attention(mesh)
    model = LlamaModel(cfg, kernel=attention_kernel)
    rng = jax.random.PRNGKey(rng_seed)
    sample = jnp.zeros((batch_size, seq_len), dtype=jnp.int32)

    with mesh:
        params = jax.jit(lambda r: model.init(r, sample))(rng)["params"]
        params = shard_params(mesh, params, llama_param_rules())
        tx = optax.adamw(learning_rate)
        opt_state = jax.jit(tx.init)(params)

        def loss_fn(p, tokens):
            logits = model.apply({"params": p}, tokens)
            return causal_lm_loss(logits, tokens)

        from functools import partial

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(p, o, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(p, tokens)
            updates, o = tx.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return p, o, loss

    def step_fn(p, o, tokens):
        tokens = shard_batch(mesh, tokens)
        with mesh:
            return step(p, o, tokens)

    return params, opt_state, step_fn, model


def param_count(params) -> int:
    import jax

    return sum(x.size for x in jax.tree_util.tree_leaves(params))
