"""GSPMD training-step construction for the flagship model.

This is the TPU-native equivalent of the reference's prepare_model
DDP/FSDP wrapping (reference: python/ray/train/torch/train_loop_utils.py
:158-186): instead of wrapping a module, we place parameters with
PartitionSpecs on a named mesh and jit one train step; XLA inserts the
all-gathers/reduce-scatters (fsdp), all-reduces (dp) and collective
matmuls (tp) over ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple


def build_llama_train_state(cfg, mesh, rng_seed: int = 0,
                            learning_rate: float = 3e-4,
                            batch_size: int = 8, seq_len: int = 128,
                            attention_kernel: Optional[Callable] = None):
    """Init sharded (params, opt_state) and a jitted train step.

    Returns (params, opt_state, step_fn, model) where
    step_fn(params, opt_state, tokens) -> (params, opt_state, loss).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import (LlamaModel, causal_lm_loss,
                                      llama_param_rules)
    from ray_tpu.parallel.mesh import shard_batch, shard_params

    if attention_kernel is None and mesh.shape.get("sp", 1) > 1:
        # sequence-parallel mesh: ring attention rotates KV over ICI
        from ray_tpu.ops.ring_attention import make_ring_attention

        attention_kernel = make_ring_attention(mesh)
    model = LlamaModel(cfg, kernel=attention_kernel)
    rng = jax.random.PRNGKey(rng_seed)
    sample = jnp.zeros((batch_size, seq_len), dtype=jnp.int32)

    with mesh:
        params = jax.jit(lambda r: model.init(r, sample))(rng)["params"]
        params = shard_params(mesh, params, llama_param_rules())
        tx = optax.adamw(learning_rate)
        opt_state = jax.jit(tx.init)(params)

        def loss_fn(p, tokens):
            logits = model.apply({"params": p}, tokens)
            return causal_lm_loss(logits, tokens)

        from functools import partial

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(p, o, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(p, tokens)
            updates, o = tx.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return p, o, loss

    def step_fn(p, o, tokens):
        tokens = shard_batch(mesh, tokens)
        with mesh:
            return step(p, o, tokens)

    return params, opt_state, step_fn, model


def build_llama_stage_state(cfg, mesh, layer_range, *, first: bool,
                            last: bool, microbatch_size: int, seq_len: int,
                            num_microbatches: int, rng_seed: int = 0,
                            learning_rate: float = 3e-4,
                            attention_kernel: Optional[Callable] = None):
    """Init one MPMD pipeline stage: sharded (params, opt_state) on the
    IN-STAGE mesh (fsdp/sp/tp — ``pp`` multiplies this layout instead of
    replacing it) plus the jitted stage functions the 1F1B loop replays.

    Returns a dict:
      params, opt_state           sharded stage subtree + adamw state
      fwd(p, x) -> y              stage forward (None for the last stage,
                                  whose forward fuses into the loss bwd)
      bwd(p, x, gy) -> (gp, gx)   recompute-backward: re-runs the stage
                                  forward inside the vjp (same FLOP trade
                                  as cfg.remat) so only the stage INPUT is
                                  kept resident per in-flight microbatch
      loss_bwd(p, x, tokens) -> (loss, gp[, gx])   last stage only
      opt_step(p, o, acc) -> (p, o)   adamw on accumulated grads / m
      accum(acc, g) -> acc        donating grad accumulator
      zero_grads(p) -> acc        fresh accumulator
      shard_value(x) -> x         device_put a microbatch onto the mesh
    """
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import (LlamaStage, causal_lm_loss,
                                      llama_param_rules)
    from ray_tpu.parallel.mesh import shard_batch, shard_params

    if attention_kernel is None and mesh.shape.get("sp", 1) > 1:
        from ray_tpu.ops.ring_attention import make_ring_attention

        attention_kernel = make_ring_attention(mesh)
    start, end = layer_range
    model = LlamaStage(cfg, start=start, end=end, first=first, last=last,
                       kernel=attention_kernel)
    rng = jax.random.PRNGKey(rng_seed)
    if first:
        sample = jnp.zeros((microbatch_size, seq_len), dtype=jnp.int32)
    else:
        sample = jnp.zeros((microbatch_size, seq_len, cfg.dim),
                           dtype=cfg.dtype)
    scale = 1.0 / float(num_microbatches)
    from functools import partial

    with mesh:
        params = jax.jit(lambda r: model.init(r, sample))(rng)["params"]
        params = shard_params(mesh, params, llama_param_rules())
        tx = optax.adamw(learning_rate)
        opt_state = jax.jit(tx.init)(params)

        def apply_fn(p, x):
            return model.apply({"params": p}, x)

        fwd = None if last else jax.jit(apply_fn)

        loss_bwd = None
        bwd = None
        if last:
            def loss_fn(p, x, tokens):
                return causal_lm_loss(apply_fn(p, x), tokens)

            if first:  # degenerate pp=1 stage: tokens in, no gx out
                @jax.jit
                def loss_bwd(p, x, tokens):
                    loss, gp = jax.value_and_grad(loss_fn)(p, x, tokens)
                    return loss, gp
            else:
                @jax.jit
                def loss_bwd(p, x, tokens):
                    loss, (gp, gx) = jax.value_and_grad(
                        loss_fn, argnums=(0, 1))(p, x, tokens)
                    return loss, gp, gx
        elif first:
            @jax.jit
            def bwd(p, x, gy):
                _, vjp = jax.vjp(lambda p_: apply_fn(p_, x), p)
                (gp,) = vjp(gy)
                return gp, None
        else:
            @jax.jit
            def bwd(p, x, gy):
                _, vjp = jax.vjp(apply_fn, p, x)
                return vjp(gy)

        @partial(jax.jit, donate_argnums=(0,))
        def accum(acc, g):
            return jax.tree_util.tree_map(jnp.add, acc, g)

        zero_grads = jax.jit(
            lambda p: jax.tree_util.tree_map(jnp.zeros_like, p))

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def opt_step(p, o, acc):
            g = jax.tree_util.tree_map(lambda a: a * scale, acc)
            updates, o = tx.update(g, o, p)
            p = optax.apply_updates(p, updates)
            return p, o

    def run_in_mesh(fn):
        def wrapped(*args):
            with mesh:
                return fn(*args)
        return wrapped

    return {
        "params": params, "opt_state": opt_state,
        "fwd": run_in_mesh(fwd) if fwd is not None else None,
        "bwd": run_in_mesh(bwd) if bwd is not None else None,
        "loss_bwd": run_in_mesh(loss_bwd) if loss_bwd is not None else None,
        "opt_step": run_in_mesh(opt_step),
        "accum": run_in_mesh(accum),
        "zero_grads": run_in_mesh(zero_grads),
        "shard_value": lambda x: shard_batch(mesh, x),
        "model": model, "mesh": mesh,
    }


def param_count(params) -> int:
    import jax

    return sum(x.size for x in jax.tree_util.tree_leaves(params))
