"""Checkpoint/result storage over fsspec URIs (local, memory, s3, gs).

Equivalent of the reference's StorageContext
(reference: python/ray/train/_internal/storage.py:1 — a pyarrow.fs
wrapper giving trainers one storage_path that may be local or remote;
checkpoints are uploaded after local save and downloaded before
restore).  TPU slant unchanged: orbax writes shards locally per host;
this layer only moves the finished checkpoint directory.

Backends:
  /abs/path or file://...  local filesystem (no copy when already local)
  memory://...             in-process fs (tests)
  s3://... gs://...        via fsspec, when the optional driver
                           (s3fs/gcsfs) is importable — otherwise a
                           clear error at construction, not mid-train
"""

from __future__ import annotations

import os
import posixpath
from typing import Optional, Tuple


def _split(uri: str) -> Tuple[str, str]:
    """(protocol, path)."""
    if "://" not in uri:
        return "file", os.path.abspath(uri)
    proto, rest = uri.split("://", 1)
    if proto == "file":
        return "file", os.path.abspath("/" + rest.lstrip("/"))
    return proto, rest


class StorageContext:
    def __init__(self, storage_path: str,
                 experiment_name: str = ""):
        self.protocol, base = _split(storage_path)
        self.experiment_path = (
            posixpath.join(base, experiment_name) if experiment_name else base)
        if self.protocol == "file":
            self.fs = None
            os.makedirs(self.experiment_path, exist_ok=True)
        else:
            try:
                import fsspec

                self.fs = fsspec.filesystem(self.protocol)
            except (ImportError, ValueError) as exc:
                raise ValueError(
                    f"storage protocol {self.protocol!r} needs an fsspec "
                    f"driver (e.g. s3fs/gcsfs): {exc}") from exc
            self.fs.makedirs(self.experiment_path, exist_ok=True)

    @property
    def is_remote(self) -> bool:
        return self.protocol != "file"

    def uri(self, *parts: str) -> str:
        path = posixpath.join(self.experiment_path, *parts)
        return path if self.protocol == "file" \
            else f"{self.protocol}://{path}"

    # ------------------------------------------------------------- dirs

    def persist_dir(self, local_dir: str, rel: str) -> str:
        """Upload a finished local directory to <experiment>/<rel>;
        returns the storage URI.  Local storage: no copy if already in
        place, else a directory copy."""
        dest = posixpath.join(self.experiment_path, rel)
        if self.protocol == "file":
            import shutil

            if os.path.abspath(local_dir) != dest:
                if os.path.exists(dest):
                    shutil.rmtree(dest)
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                shutil.copytree(local_dir, dest)
            return dest
        self.fs.put(local_dir, dest, recursive=True)
        return f"{self.protocol}://{dest}"

    def fetch_dir(self, rel_or_uri: str, local_dir: str) -> str:
        """Download <experiment>/<rel> (or a full URI) into local_dir;
        returns the local path (which IS the storage path when local)."""
        proto, path = _split(rel_or_uri) if "://" in rel_or_uri \
            else (self.protocol, posixpath.join(self.experiment_path,
                                                rel_or_uri))
        if proto == "file":
            return path
        import shutil

        if os.path.exists(local_dir):
            shutil.rmtree(local_dir)
        # per-file download keyed on the source listing: deterministic
        # layout regardless of how a backend's recursive get nests dirs.
        # find() returns backend-normalized paths — normalize the base
        # the same way so relpath stays inside the tree.
        src = self.fs._strip_protocol(path.rstrip("/"))
        for remote_path, info in self.fs.find(src, withdirs=True,
                                              detail=True).items():
            rel = posixpath.relpath(remote_path, src)
            dest = os.path.join(local_dir, rel)
            if info.get("type") == "directory":
                os.makedirs(dest, exist_ok=True)  # keep empty dirs
                continue
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            self.fs.get_file(remote_path, dest)
        os.makedirs(local_dir, exist_ok=True)
        return local_dir

    # ------------------------------------------------------------ files

    def write_bytes(self, rel: str, data: bytes) -> None:
        """Binary sibling of write_text (actor-state snapshots ride
        this); local writes are atomic tmp+rename like the text path."""
        if self.protocol == "file":
            path = posixpath.join(self.experiment_path, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
            return
        with self.fs.open(posixpath.join(self.experiment_path, rel),
                          "wb") as f:
            f.write(data)

    def read_bytes(self, rel: str) -> Optional[bytes]:
        try:
            if self.protocol == "file":
                with open(posixpath.join(self.experiment_path, rel),
                          "rb") as f:
                    return f.read()
            with self.fs.open(posixpath.join(self.experiment_path, rel),
                              "rb") as f:
                return f.read()
        except (OSError, FileNotFoundError):
            return None

    def remove(self, rel: str) -> None:
        """Best-effort single-file delete (snapshot eviction)."""
        try:
            if self.protocol == "file":
                os.remove(posixpath.join(self.experiment_path, rel))
            else:
                self.fs.rm_file(posixpath.join(self.experiment_path, rel))
        except (OSError, FileNotFoundError):
            pass

    def write_text(self, rel: str, text: str) -> None:
        if self.protocol == "file":
            path = posixpath.join(self.experiment_path, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
            return
        with self.fs.open(posixpath.join(self.experiment_path, rel),
                          "w") as f:
            f.write(text)

    def read_text(self, rel: str) -> Optional[str]:
        try:
            if self.protocol == "file":
                with open(posixpath.join(self.experiment_path, rel)) as f:
                    return f.read()
            with self.fs.open(posixpath.join(self.experiment_path, rel),
                              "r") as f:
                return f.read()
        except (OSError, FileNotFoundError):
            return None

    def list_dir(self, rel: str = "") -> list:
        path = posixpath.join(self.experiment_path, rel) if rel \
            else self.experiment_path
        try:
            if self.protocol == "file":
                return sorted(os.listdir(path))
            return sorted(posixpath.basename(p.rstrip("/"))
                          for p in self.fs.ls(path, detail=False))
        except (OSError, FileNotFoundError):
            return []
