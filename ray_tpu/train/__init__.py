"""ray_tpu.train: distributed training on TPU meshes via actor gangs.

Equivalent of Ray Train (reference: python/ray/train/ —
DataParallelTrainer data_parallel_trainer.py:22, BackendExecutor
_internal/backend_executor.py:65, session _internal/session.py:109), with
the torch process-group layer replaced by `jax.distributed` + GSPMD
meshes: parallelism is declared as a MeshSpec (dp/fsdp/tp/sp/pp) instead
of wrapping modules in DDP/FSDP.
"""

from ray_tpu.train.checkpoint import (CheckpointManager, restore_checkpoint,
                                      save_checkpoint)
from ray_tpu.train.storage import StorageContext
from ray_tpu.train.session import (TrainContext, get_context, report,
                                   get_checkpoint, get_dataset_shard)
from ray_tpu.train.trainer import (JaxTrainer, Result, RunConfig,
                                   ScalingConfig, TrainingFailedError)
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.train.pipeline import (PipelineError, TrainPipeline,
                                    one_f_one_b, partition_layers)

__all__ = ["JaxTrainer", "ScalingConfig", "RunConfig", "Result",
           "TrainingFailedError", "WorkerGroup", "TrainContext",
           "get_context", "report", "get_checkpoint", "get_dataset_shard",
           "save_checkpoint", "restore_checkpoint", "CheckpointManager",
           "StorageContext", "TrainPipeline", "PipelineError",
           "partition_layers", "one_f_one_b"]
