"""JaxTrainer: SPMD training over a gang of TPU workers.

Equivalent of the reference's DataParallelTrainer + BackendExecutor
(reference: python/ray/train/data_parallel_trainer.py:22 training_loop
:420; _internal/backend_executor.py:65 — placement :197, rank mapping
:347, start_training :427, get_next_results :541), with torch process
groups replaced by jax.distributed + GSPMD meshes:

  - ScalingConfig declares workers and per-worker resources (TPU chips)
  - the parallelism layout travels as a MeshSpec in train_loop_config;
    inside the loop, `make_mesh(spec)` builds the mesh over the global
    device view (all hosts' chips after jax.distributed.initialize)
  - worker failure fails the run (Train is not elastic in the reference
    either — SURVEY §5.3; restart-from-checkpoint is the recovery path)
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(Exception):
    pass


@dataclass
class ScalingConfig:
    """Reference: python/ray/air/config.py ScalingConfig."""

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        return {"TPU": 4} if self.use_tpu else {}


@dataclass
class RunConfig:
    name: str = "train_run"
    storage_path: str = "/tmp/ray_tpu_results"
    failure_max_retries: int = 0


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint: Optional[str] = None
    error: Optional[BaseException] = None
    per_worker_final: List[Any] = field(default_factory=list)


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable,
                 *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[str] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self.train_loop = train_loop_per_worker
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.config = train_loop_config
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    def fit(self) -> Result:
        """Run to completion, rebuilding the worker gang and resuming from
        the last reported checkpoint on failure, up to
        RunConfig.failure_max_retries times (reference:
        backend_executor.get_with_failure_handling :629 +
        tune_controller._schedule_trial_restore :1792 — Train is gang-
        restart, not elastic)."""
        resume = self.resume_from_checkpoint
        history: List[Dict[str, Any]] = []
        failures = 0
        while True:
            group = WorkerGroup(self.scaling.num_workers,
                                self.scaling.worker_resources())
            try:
                return self._fit(group, resume, history)
            except TrainingFailedError as e:
                ckpt = getattr(e, "last_checkpoint", None)
                if ckpt:
                    resume = ckpt
                if failures >= self.run_config.failure_max_retries:
                    raise
                failures += 1
            finally:
                group.shutdown()

    def _fit(self, group: WorkerGroup, resume: Optional[str] = None,
             history: Optional[List[Dict[str, Any]]] = None) -> Result:
        import os

        n = group.num_workers
        trial_dir = os.path.join(self.run_config.storage_path,
                                 f"{self.run_config.name}-{int(time.time())}")
        os.makedirs(trial_dir, exist_ok=True)
        import ray_tpu

        # multi-process rendezvous (reference: backend_executor start —
        # rank 0 address/port shared with the gang before the loop starts)
        if n > 1:
            try:
                info0 = group.execute_single(0, "node_info")
                port = group.execute_single(0, "free_port")
                coordinator = f"{info0['ip']}:{port}"
                self._init_distributed(group, coordinator, n)
            except ray_tpu.RayError as e:
                err = TrainingFailedError(
                    f"worker gang failed during rendezvous: {e}")
                err.last_checkpoint = resume
                raise err from e
        fn_blob = cloudpickle.dumps(self.train_loop)
        # dataset ingest: each worker gets its round-robin block shard
        # (reference: _internal/data_config.py streaming_split)
        shard_map: Dict[int, Dict[str, Any]] = {r: {} for r in range(n)}
        for name, ds in self.datasets.items():
            for rank, shard in enumerate(ds.split(n)):
                shard_map[rank][name] = shard
        import ray_tpu

        refs = []
        for rank, w in enumerate(group.workers):
            refs.append(w.run_async.remote(
                fn_blob, self.config, checkpoint=resume,
                experiment_name=self.run_config.name, trial_dir=trial_dir,
                datasets=shard_map[rank] or None))
        try:
            ray_tpu.get(refs, timeout=120.0)
        except ray_tpu.RayError as e:
            err = TrainingFailedError(f"worker gang failed to launch: {e}")
            err.last_checkpoint = resume
            raise err from e
        return self._poll_until_done(group, trial_dir, history)

    def _init_distributed(self, group: WorkerGroup, coordinator: str, n: int):
        import ray_tpu

        refs = [w.init_jax_distributed.remote(coordinator, n, rank)
                for rank, w in enumerate(group.workers)]
        ray_tpu.get(refs, timeout=300.0)

    def _poll_until_done(self, group: WorkerGroup, trial_dir: str,
                         history: Optional[List[Dict[str, Any]]] = None) -> Result:
        import ray_tpu

        history = history if history is not None else []
        last_checkpoint: Optional[str] = None
        done = [False] * group.num_workers
        finals: List[Any] = [None] * group.num_workers

        def _fail(msg: str, cause: BaseException):
            err = TrainingFailedError(msg)
            err.last_checkpoint = last_checkpoint  # resume point for fit()
            raise err from cause

        while not all(done):
            time.sleep(0.05)
            try:
                polls = group.execute("poll", timeout=120.0)
            except (ray_tpu.ActorDiedError, ray_tpu.RayError) as e:
                _fail(f"a training worker died mid-run: {e}", e)
            for rank, p in enumerate(polls):
                for rep in p["reports"]:
                    if rank == 0 and "_error" not in rep["metrics"]:
                        history.append(rep["metrics"])
                    if rep.get("checkpoint"):
                        last_checkpoint = rep["checkpoint"]
                if p["done"] and not done[rank]:
                    done[rank] = True
                    if p["error"] is not None:
                        err = cloudpickle.loads(p["error"])
                        _fail(f"train loop failed on rank {rank}: {err}", err)
                    finals[rank] = p["final"]
        return Result(metrics=history[-1] if history else {},
                      metrics_history=history,
                      checkpoint=last_checkpoint,
                      per_worker_final=finals)
