"""MPMD pipeline-parallel training on compiled-graph channels.

"Scaling Deep Learning Training with MPMD Pipeline Parallelism"
(PAPERS.md, arxiv 2412.14374) splits the model into per-stage XLA
programs connected by explicit channels instead of one giant SPMD
program; "Exploring the limits of Concurrency in ML Training on Google
TPUs" (arxiv 2011.03641) frames the objective — keep every stage busy,
not every chip at peak FLOPs.  This module is the framework's MPMD
runtime: the ``pp`` mesh axis becomes REAL processes.

Architecture (one optimizer step, S stages, m microbatches):

    driver ──tokens──▶ [stage 0] ──act──▶ [stage 1] ─ … ─▶ [stage S-1]
       │                   ◀──grad──          ◀──grad──        │ ▲tokens
       └────────────────◀──────── per-stage reports ◀──────────┘

  * :func:`partition_layers` splits the Llama stack into contiguous,
    param/FLOP-balanced layer ranges (embedding weighted onto stage 0,
    the lm_head matmul onto the last stage).
  * One :class:`PipelineStage` actor per stage builds its own IN-STAGE
    ``jax`` mesh (fsdp/sp/tp via train/gspmd.py
    ``build_llama_stage_state``) — ``pp`` multiplies the existing
    parallelism instead of replacing it.
  * All edges are mutable compiled-graph channels (dag/channel.py):
    pre-allocated pinned shm rings, remote readers fed by bulk-plane
    pushes.  Activation channels are DEEP (ring depth bounds the
    in-flight microbatches of the 1F1B schedule) while grad/report
    channels stay shallow — the per-channel sizing the DAG layer's
    ``with_channel_options`` exposes for generic graphs.
  * Each stage runs a PINNED exec loop (worker dispatch
    ``__rt_dag_pipeline_loop__``, exactly like the compiled-DAG loop)
    replaying :func:`one_f_one_b`'s op list per step: warm-up forwards,
    steady-state 1F1B, drain, then grad-scaled adamw.  Backward
    RECOMPUTES the stage forward inside the vjp (the ``remat`` FLOP
    trade), so a stage keeps only its in-flight microbatch INPUTS.
  * The driver writes m microbatch token versions per step and reads one
    report per stage (loss from the last stage, busy-time split from
    all) — the report timestamps drive ``ray_tpu_pipeline_bubble_pct``.

Failure model: a dying stage fails its loop task; the driver monitor
poisons every channel within ``dag_monitor_interval_s`` so all blocked
parties raise instead of hanging.  With checkpointing on (``save_every``
> 0, stage actors created with ``max_restarts``), stages persist
(step, params, opt_state) through the ``__rt_save__``/``__rt_restore__``
hooks at step boundaries and :meth:`TrainPipeline.resume` rolls every
stage back to the newest COMMON snapshot step, rebuilds fresh channels,
and reinstalls the loops.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.errors import RayError
# single source of truth for the system-method names: the worker defines
# them (its _execute_inner dispatches on them); we submit with them
from ray_tpu._private.worker import (PIPELINE_CTL_METHOD,
                                     PIPELINE_EXEC_METHOD)


class PipelineError(RayError):
    pass


# ------------------------------------------------------------------ schedule


def one_f_one_b(stage: int, n_stages: int,
                n_microbatches: int) -> List[Tuple[str, int]]:
    """The 1F1B op list for one optimizer step of one stage.

    ``min(n_stages - 1 - stage, m)`` warm-up forwards, then strict
    forward/backward alternation, then the backward drain — the last
    stage alternates from op one, the first stage fills the pipe.  The
    in-flight microbatch count (forwards minus backwards) never exceeds
    :func:`in_flight_bound`, which is what sizes the activation
    channels' rings.
    """
    if not (0 <= stage < n_stages):
        raise ValueError(f"stage {stage} out of range for {n_stages}")
    if n_microbatches < 1:
        raise ValueError("need at least one microbatch")
    warmup = min(n_stages - 1 - stage, n_microbatches)
    ops: List[Tuple[str, int]] = [("F", k) for k in range(warmup)]
    f, b = warmup, 0
    while f < n_microbatches:
        ops.append(("F", f))
        f += 1
        ops.append(("B", b))
        b += 1
    while b < n_microbatches:
        ops.append(("B", b))
        b += 1
    return ops


def in_flight_bound(stage: int, n_stages: int, n_microbatches: int) -> int:
    """Max microbatches a stage holds between forward and backward."""
    return min(n_stages - stage, n_microbatches)


def bubble_pct(busy_s: Sequence[float], wall_s: float) -> float:
    """Pipeline bubble: the fraction of stage-seconds spent idle.

    ``busy_s`` is per-stage compute time over a window of ``wall_s``
    seconds; S * wall is the total stage-time available.  0 == every
    stage computed the whole window; the 1F1B analytic floor is
    (S-1)/(m+S-1) per step.
    """
    if wall_s <= 0 or not busy_s:
        return 0.0
    frac = sum(busy_s) / (len(busy_s) * wall_s)
    return 100.0 * max(0.0, min(1.0, 1.0 - frac))


# ----------------------------------------------------------------- partition


def partition_layers(cfg, n_stages: int) -> List[Tuple[int, int]]:
    """Contiguous ``[start, end)`` layer ranges, one per stage,
    minimizing the maximum per-stage cost.

    Cost model: a transformer block's fwd+bwd FLOPs are proportional to
    its parameter count; the lm_head matmul (last stage) likewise; the
    embedding lookup is FLOP-free forward but pays a scatter-add
    backward plus optimizer traffic, charged at 0.3x its params.  Every
    stage owns at least one block.
    """
    L = int(cfg.n_layers)
    if not (1 <= n_stages <= L):
        raise ValueError(f"pp={n_stages} needs 1..{L} stages "
                         f"for {L} layers")
    per_layer = float(
        cfg.dim * cfg.n_heads * cfg.head_dim
        + 2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim
        + cfg.n_heads * cfg.head_dim * cfg.dim
        + 3 * cfg.dim * cfg.hidden_dim + 2 * cfg.dim)
    embed_w = 0.3 * cfg.vocab_size * cfg.dim
    head_w = float(cfg.vocab_size * cfg.dim)

    def stage_cost(s: int, n_layers: int) -> float:
        c = n_layers * per_layer
        if s == 0:
            c += embed_w
        if s == n_stages - 1:
            c += head_w
        return c

    INF = float("inf")
    # dp[s][l]: minimal max-cost splitting the first l layers into the
    # first s stages; choice[s][l]: where stage s-1 started
    dp = [[INF] * (L + 1) for _ in range(n_stages + 1)]
    choice = [[0] * (L + 1) for _ in range(n_stages + 1)]
    dp[0][0] = 0.0
    for s in range(1, n_stages + 1):
        for l in range(s, L + 1):
            for k in range(s - 1, l):
                cost = max(dp[s - 1][k], stage_cost(s - 1, l - k))
                if cost < dp[s][l]:
                    dp[s][l] = cost
                    choice[s][l] = k
    ranges: List[Tuple[int, int]] = []
    l = L
    for s in range(n_stages, 0, -1):
        k = choice[s][l]
        ranges.append((k, l))
        l = k
    ranges.reverse()
    return ranges


def slice_params_for_stage(params: Dict[str, Any],
                           ranges: Sequence[Tuple[int, int]],
                           stage: int) -> Dict[str, Any]:
    """Select one stage's parameter subtree from a full LlamaModel tree
    (LlamaStage submodule names match LlamaModel's), e.g. to seed a
    pipeline from a single-program checkpoint."""
    d = dict(params)
    start, end = ranges[stage]
    out: Dict[str, Any] = {}
    if stage == 0 and "embed" in d:
        out["embed"] = d["embed"]
    for i in range(start, end):
        out[f"layer_{i}"] = d[f"layer_{i}"]
    if stage == len(ranges) - 1:
        for key in ("final_norm", "lm_head"):
            if key in d:
                out[key] = d[key]
    return out


# ------------------------------------------------------------- stage actor


class PipelineStage:
    """Actor hosting ONE pipeline stage: sharded params + adamw state on
    the in-stage mesh, jitted stage functions, and the pinned 1F1B loop
    (entered via the ``__rt_dag_pipeline_loop__`` system method, so the
    exec thread stays pinned exactly like a compiled-DAG loop)."""

    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec
        self.stage = int(spec["stage"])
        self.n_stages = int(spec["n_stages"])
        self.num_microbatches = int(spec["num_microbatches"])
        self._step = 0
        self._build()

    # ------------------------------------------------------------- jax state

    def _build(self) -> None:
        import jax

        from ray_tpu.parallel.mesh import MeshSpec, make_mesh
        from ray_tpu.train.gspmd import build_llama_stage_state

        spec = self.spec
        devices = jax.devices()
        off = int(spec.get("device_offset") or 0)
        count = int(spec.get("device_count") or 0)
        if count:
            devices = devices[off:off + count]
        self._mesh = make_mesh(MeshSpec(**spec.get("mesh_axes", {})),
                               devices=devices)
        start, end = spec["ranges"][self.stage]
        self._first = self.stage == 0
        self._last = self.stage == self.n_stages - 1
        self._st = build_llama_stage_state(
            spec["cfg"], self._mesh, (start, end),
            first=self._first, last=self._last,
            microbatch_size=int(spec["microbatch_size"]),
            seq_len=int(spec["seq_len"]),
            num_microbatches=self.num_microbatches,
            rng_seed=int(spec.get("rng_seed", 0)),
            learning_rate=float(spec.get("learning_rate", 3e-4)))
        initial = spec.get("initial_params")
        if initial is not None:
            self._st["params"] = self._shard_tree(initial)

    def _shard_tree(self, tree):
        from ray_tpu.models.llama import llama_param_rules
        from ray_tpu.parallel.mesh import shard_params

        return shard_params(self._mesh, tree, llama_param_rules())

    # --------------------------------------------------- save/restore hooks

    def __rt_save__(self) -> Dict[str, Any]:
        import jax
        import numpy as np

        return {
            "step": self._step,
            "params": jax.tree_util.tree_map(np.asarray,
                                             self._st["params"]),
            "opt": jax.tree_util.tree_map(np.asarray,
                                          self._st["opt_state"]),
        }

    def __rt_restore__(self, state: Dict[str, Any]) -> None:
        self._st["params"] = self._shard_tree(state["params"])
        self._st["opt_state"] = self._shard_tree(state["opt"])
        self._step = int(state["step"])

    # ------------------------------------------------------------ exec loop

    def _read(self, reader, seq: int):
        value, is_err = reader.read(seq)
        if is_err:
            raise value
        return value

    def _run_loop(self, worker, plan: Dict[str, Any]) -> Dict[str, Any]:
        import jax
        import numpy as np

        from ray_tpu.dag import channel as ch

        st = self._st
        m = self.num_microbatches
        first, last = self._first, self._last
        save_every = int(plan.get("save_every", 0))
        self._step = int(plan.get("start_step", self._step))
        chans = plan["channels"]

        def mk_reader(key):
            c = chans.get(key)
            if c is None:
                return None
            return ch.ChannelReader(ch.ChannelSpec(**c["spec"]),
                                    c["index"])

        def mk_writer(key):
            c = chans.get(key)
            if c is None:
                return None
            return ch.ChannelWriter(ch.ChannelSpec(**c["spec"]))

        in_r = mk_reader("input")     # tokens: first + last stages
        act_r = mk_reader("act_in")   # activations from upstream
        gy_r = mk_reader("grad_in")   # activation grads from downstream
        act_w = mk_writer("act_out")
        gx_w = mk_writer("grad_out")
        rep_w = mk_writer("report")
        ops = one_f_one_b(self.stage, self.n_stages, m)
        t_local = 0
        completed = 0

        def take(reader, seq, shard=True):
            """Blocking read -> device array; the ring slot is released
            only after device_put completes (the deserialized value
            aliases ring memory)."""
            value = self._read(reader, seq)
            out = st["shard_value"](value) if shard else value
            jax.block_until_ready(out)
            reader.advance(seq)
            return out

        try:
            while True:
                base = t_local * m
                inputs: Dict[int, Any] = {}
                pending: Dict[int, Tuple[float, Any, Any]] = {}
                acc = None
                loss_sum = 0.0
                fwd_s = bwd_s = 0.0
                t_step0 = time.perf_counter()
                for op, k in ops:
                    seq = base + k + 1
                    if op == "F":
                        x = take(act_r if not first else in_r, seq)
                        if last:
                            targets = take(in_r, seq)
                            t0 = time.perf_counter()
                            if first:  # degenerate single-stage
                                loss, gp = st["loss_bwd"](
                                    st["params"], x, targets)
                                gx = None
                            else:
                                loss, gp, gx = st["loss_bwd"](
                                    st["params"], x, targets)
                            loss = float(loss)  # syncs the fused step
                            bwd_s += time.perf_counter() - t0
                            pending[k] = (loss, gp, gx)
                        else:
                            t0 = time.perf_counter()
                            y = st["fwd"](st["params"], x)
                            y_host = np.asarray(y)  # sync
                            fwd_s += time.perf_counter() - t0
                            act_w.write(y_host)
                            inputs[k] = x
                    else:  # "B"
                        if last:
                            loss, gp, gx = pending.pop(k)
                            loss_sum += loss
                            if gx_w is not None:
                                t0 = time.perf_counter()
                                gx_host = np.asarray(gx)  # sync residue
                                bwd_s += time.perf_counter() - t0
                                gx_w.write(gx_host)
                        else:
                            gy = take(gy_r, seq)
                            x = inputs.pop(k)
                            t0 = time.perf_counter()
                            gp, gx = st["bwd"](st["params"], x, gy)
                            if gx_w is not None:
                                gx_host = np.asarray(gx)  # sync
                            else:
                                jax.block_until_ready(gp)
                            bwd_s += time.perf_counter() - t0
                            if gx_w is not None:
                                gx_w.write(gx_host)
                        acc = gp if acc is None else st["accum"](acc, gp)
                t0 = time.perf_counter()
                p, o = st["opt_step"](st["params"], st["opt_state"], acc)
                jax.block_until_ready(p)
                opt_s = time.perf_counter() - t0
                st["params"], st["opt_state"] = p, o
                self._step += 1
                wall = time.perf_counter() - t_step0
                if save_every > 0 and self._step % save_every == 0:
                    worker.persist_actor_state()
                rep_w.write({
                    "stage": self.stage, "step": self._step,
                    "loss": (loss_sum / m) if last else None,
                    "fwd_s": fwd_s, "bwd_s": bwd_s, "opt_s": opt_s,
                    "busy_s": fwd_s + bwd_s + opt_s, "wall_s": wall,
                })
                t_local += 1
                completed += 1
        except ch.ChannelClosedError:
            pass  # clean teardown
        finally:
            for writer in (act_w, gx_w, rep_w):
                if writer is not None:
                    try:
                        writer.close()
                    except Exception:
                        pass
                    writer.detach()
        return {"steps_completed": completed, "step": self._step}


def run_stage_loop(worker, instance, plan: Dict[str, Any]) -> Dict[str, Any]:
    """Worker-dispatch target for ``__rt_dag_pipeline_loop__``."""
    return instance._run_loop(worker, plan)


def run_stage_ctl(worker, instance, req: Dict[str, Any]) -> Dict[str, Any]:
    """Worker-dispatch target for ``__rt_dag_pipeline_ctl__`` — control
    ops that need the worker (checkpoint store access) without tripping
    the per-method autosave (system methods are exempt), so recovery
    probes can never evict the snapshots they are about to restore."""
    import os

    op = req.get("op")
    if op == "info":
        return {"pid": os.getpid(), "step": instance._step,
                "node_id": worker.node_id, "stage": instance.stage}
    if op == "save_now":
        return {"saved": worker.persist_actor_state(),
                "step": instance._step}
    spec = worker._actor_creation_spec
    ckpt = worker._actor_state_checkpoint(spec.actor_id) \
        if spec is not None and spec.actor_id else None
    if op == "snapshot_steps":
        steps: Dict[int, str] = {}
        if ckpt is not None:
            for name in ckpt.entry_names():
                state = ckpt.load_entry(name)
                if isinstance(state, dict) and "step" in state:
                    steps[int(state["step"])] = name
        return {"steps": sorted(steps)}
    if op == "load_snapshot":
        want = int(req["step"])
        if ckpt is not None:
            for name in reversed(ckpt.entry_names()):
                state = ckpt.load_entry(name)
                if isinstance(state, dict) \
                        and int(state.get("step", -1)) == want:
                    instance.__rt_restore__(state)
                    return {"ok": True, "step": want}
        return {"ok": False, "step": want}
    raise ValueError(f"unknown pipeline ctl op {op!r}")


# ------------------------------------------------------------------- driver


class TrainPipeline:
    """Driver handle for an MPMD pipeline-parallel training run.

    ``step(tokens)`` feeds one global batch (``microbatch_size *
    num_microbatches`` rows) through the 1F1B pipeline and returns the
    step's loss + per-stage busy/bubble accounting.  The driver holds no
    jax state — stages own their shards; the driver only moves token
    microbatches and reads reports.

    Checkpointing cost: with ``max_restarts > 0``, ``save_every``
    defaults to 1 — every optimizer step each stage materializes params
    + adamw state to host numpy and cloudpickles them through the
    actor-state storage layer.  Cheap at test scale, dominant at real
    model scale: pass an explicit ``save_every`` sized to your step
    time (the resume protocol only needs SOME common saved step, and
    rolls back to the newest one).
    """

    def __init__(self, cfg, *, pp: int, microbatch_size: int,
                 num_microbatches: int, seq_len: int,
                 stage_mesh: Optional[Dict[str, int]] = None,
                 learning_rate: float = 3e-4, rng_seed: int = 0,
                 initial_params: Optional[Dict[str, Any]] = None,
                 devices_per_stage: int = 0,
                 resources_per_stage: Optional[Dict[str, float]] = None,
                 max_restarts: int = 0, save_every: Optional[int] = None,
                 act_depth: Optional[int] = None, grad_depth: int = 2,
                 step_timeout: float = 600.0,
                 compile_timeout: float = 300.0):
        if pp < 2:
            raise ValueError("pipeline parallelism needs pp >= 2 "
                             "(use train/gspmd.py single-program "
                             "training for pp=1)")
        self.cfg = cfg
        self.pp = pp
        self.microbatch_size = int(microbatch_size)
        self.num_microbatches = int(num_microbatches)
        self.seq_len = int(seq_len)
        self._lr = float(learning_rate)
        self._rng_seed = int(rng_seed)
        self._stage_mesh = dict(stage_mesh or {})
        self._stage_mesh.pop("pp", None)  # pp is the actor axis here
        self._ranges = partition_layers(cfg, pp)
        self._save_every = (1 if max_restarts > 0 else 0) \
            if save_every is None else int(save_every)
        # the activation ring depth IS the schedule's in-flight bound:
        # 1F1B holds at most `pp` microbatches between fwd and bwd
        self._act_depth = int(act_depth or (pp + 1))
        self._grad_depth = int(grad_depth)
        self._step_timeout = float(step_timeout)
        self._run_id = uuid.uuid4().hex[:10]
        self._generation = 0
        self._torn_down = False
        self._teardown_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._local_step = 0     # steps within the current loop generation
        self._global_step = 0
        self._in_writer = None
        self._rep_readers: List[Any] = []
        self._loop_refs: List[Any] = []

        from ray_tpu.dag.execution import ChannelHost

        self._channels = ChannelHost()
        try:
            self._create_actors(initial_params, resources_per_stage,
                                max_restarts, devices_per_stage)
            if self._save_every > 0:
                for h in self._handles:  # step-0 snapshots so resume()
                    self._ctl(h, {"op": "save_now"})  # always has a base
            self._wire(start_step=0, timeout=compile_timeout)
        except BaseException:
            try:
                self.teardown(timeout=5.0)
            except Exception:
                pass
            raise

    # -------------------------------------------------------------- setup

    def _create_actors(self, initial_params, resources, max_restarts,
                       devices_per_stage) -> None:
        import ray_tpu

        cls = ray_tpu.remote(PipelineStage)
        # a second exec thread serves control ops (info/snapshot/
        # restore probes) while the 1F1B loop pins the first
        opts: Dict[str, Any] = {"max_restarts": int(max_restarts),
                                "max_concurrency": 2}
        if resources:
            opts["resources"] = dict(resources)
        self._handles = []
        for s in range(self.pp):
            spec = {
                "stage": s, "n_stages": self.pp, "cfg": self.cfg,
                "ranges": list(self._ranges),
                "mesh_axes": dict(self._stage_mesh),
                "microbatch_size": self.microbatch_size,
                "seq_len": self.seq_len,
                "num_microbatches": self.num_microbatches,
                "learning_rate": self._lr,
                # one root key for every stage: flax folds per-parameter
                # keys by module path, and LlamaStage reuses LlamaModel's
                # submodule names, so stage init matches a sliced
                # full-model init (initial_params overrides regardless)
                "rng_seed": self._rng_seed,
                "device_offset": s * int(devices_per_stage),
                "device_count": int(devices_per_stage),
            }
            if initial_params is not None:
                spec["initial_params"] = slice_params_for_stage(
                    initial_params, self._ranges, s)
            self._handles.append(cls.options(**opts).remote(spec))

    def _ctl(self, handle, req: Dict[str, Any], timeout: float = 120.0):
        import ray_tpu
        from ray_tpu import api as _api

        w = _api._worker()
        ref = w.submit_actor_task(handle._actor_id, PIPELINE_CTL_METHOD,
                                  (req,), {})[0]
        return ray_tpu.get(ref, timeout=timeout)

    def _wire(self, start_step: int, timeout: float) -> None:
        """Fetch placement, allocate this generation's channels, attach
        driver endpoints, install the stage loops, start the monitor."""
        import numpy as np

        import ray_tpu
        from ray_tpu import api as _api
        from ray_tpu.dag import channel as ch
        from ray_tpu.dag.execution import DAG_INFO_METHOD

        w = _api._worker()
        info_refs = [w.submit_actor_task(h._actor_id, DAG_INFO_METHOD,
                                         (), {})[0]
                     for h in self._handles]
        infos = ray_tpu.get(info_refs, timeout=timeout)
        try:
            xfer_port = int(w.agent.call("node_info").get("xfer_port") or 0)
        except Exception:
            xfer_port = 0
        driver_info = {"node_id": w.node_id, "agent": list(w.agent_addr),
                       "xfer_port": xfer_port}
        entities = {"driver": driver_info,
                    **{s: infos[s] for s in range(self.pp)}}
        node_table = {i["node_id"]: {"agent": i["agent"],
                                     "xfer_port": i["xfer_port"]}
                      for i in entities.values()}

        # activations and activation-grads travel in the model's compute
        # dtype (bf16 by default, but cfg.dtype is a public knob)
        itemsize_act = int(np.dtype(self.cfg.dtype).itemsize)
        act_bytes = self.microbatch_size * self.seq_len \
            * int(self.cfg.dim) * itemsize_act
        tok_bytes = self.microbatch_size * self.seq_len * 8
        S, m = self.pp, self.num_microbatches
        gen = self._generation

        def pad(n: int) -> int:
            return n + n // 8 + 8192  # serialization header + margin

        def make_spec(name, writer, readers, depth, slot) -> ch.ChannelSpec:
            wnode = entities[writer]["node_id"]
            rnodes = [entities[r]["node_id"] for r in readers]
            involved = dict.fromkeys([wnode] + rnodes)
            return ch.ChannelSpec(
                oid=f"pipech-{self._run_id}-g{gen}-{name}",
                max_in_flight=depth, slot_size=pad(slot),
                n_readers=len(readers), writer_node=wnode,
                reader_nodes=rnodes,
                nodes={nid: node_table[nid] for nid in involved})

        in_depth = max(2, min(m, 64))
        input_spec = make_spec("in", "driver", [0, S - 1], in_depth,
                               tok_bytes)
        act_specs = [make_spec(f"act{i}", i, [i + 1], self._act_depth,
                               act_bytes) for i in range(S - 1)]
        grad_specs = [make_spec(f"grad{i}", i + 1, [i], self._grad_depth,
                                act_bytes) for i in range(S - 1)]
        rep_specs = [make_spec(f"rep{i}", i, ["driver"], 4, 32768)
                     for i in range(S)]
        for spec in [input_spec] + act_specs + grad_specs + rep_specs:
            self._channels.create(spec)
        from ray_tpu.dag.execution import _register_live_channels

        # claim the slots so the head's channel-leak tripwire can tell a
        # live pipeline's pinned rings from an abandoned graph's
        _register_live_channels(id(self), self._channels.oids())

        self._in_writer = ch.ChannelWriter(input_spec)
        self._rep_readers = [ch.ChannelReader(spec, 0)
                             for spec in rep_specs]

        self._loop_refs = []
        for s, h in enumerate(self._handles):
            chans: Dict[str, Any] = {
                "report": {"spec": dataclasses.asdict(rep_specs[s])}}
            if s == 0 or s == S - 1:
                chans["input"] = {
                    "spec": dataclasses.asdict(input_spec),
                    "index": 0 if s == 0 else 1}
            if s > 0:
                chans["act_in"] = {
                    "spec": dataclasses.asdict(act_specs[s - 1]),
                    "index": 0}
                chans["grad_out"] = {
                    "spec": dataclasses.asdict(grad_specs[s - 1])}
            if s < S - 1:
                chans["act_out"] = {
                    "spec": dataclasses.asdict(act_specs[s])}
                chans["grad_in"] = {
                    "spec": dataclasses.asdict(grad_specs[s]),
                    "index": 0}
            plan = {"channels": chans, "start_step": start_step,
                    "save_every": self._save_every}
            self._loop_refs.append(w.submit_actor_task(
                h._actor_id, PIPELINE_EXEC_METHOD, (plan,), {})[0])
        self._local_step = 0
        self._global_step = start_step
        self._monitor_stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop,
            args=(list(self._loop_refs), self._monitor_stop),
            name=f"rt-pipeline-monitor-{self._run_id}", daemon=True)
        self._monitor.start()

    # -------------------------------------------------------- death watch

    def _monitor_loop(self, refs: List[Any], stop: threading.Event) -> None:
        import ray_tpu
        from ray_tpu._private.config import config

        interval = float(config.dag_monitor_interval_s)
        while refs and not stop.is_set():
            try:
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=interval)
            except Exception:
                return  # driver shutting down
            if self._torn_down or stop.is_set():
                return
            for ref in ready:
                try:
                    ray_tpu.get(ref, timeout=0)
                    # a loop returning outside teardown is itself fatal:
                    # the pipeline can no longer make progress
                    self._fail(PipelineError(
                        "pipeline stage loop exited unexpectedly"))
                except Exception as e:  # noqa: BLE001 — stage death
                    self._fail(e if isinstance(e, RayError) else
                               PipelineError(f"pipeline stage failed: {e}"))
                return

    def _fail(self, error: BaseException) -> None:
        if self._error is not None:
            return
        from ray_tpu.dag import channel as ch

        self._error = error
        self._channels.poison_all(ch.pickle_error(error))

    def _check_failure(self) -> None:
        if self._error is not None:
            raise self._error
        if self._torn_down:
            raise PipelineError("this TrainPipeline has been torn down")

    # ----------------------------------------------------------- training

    @property
    def global_batch_size(self) -> int:
        return self.microbatch_size * self.num_microbatches

    def step(self, tokens) -> Dict[str, Any]:
        """One optimizer step: split ``tokens`` [B, S] into microbatches,
        stream them through the pipeline, read every stage's report.
        Returns loss (last stage), wall time, tokens/s, bubble %, and
        the raw per-stage reports."""
        import numpy as np

        from ray_tpu._private.metrics import pipeline_metrics

        self._check_failure()
        tokens = np.ascontiguousarray(tokens)
        B = tokens.shape[0]
        if B != self.global_batch_size:
            raise ValueError(
                f"batch dim {B} != microbatch_size*num_microbatches "
                f"({self.global_batch_size})")
        mb = self.microbatch_size
        t0 = time.perf_counter()
        for k in range(self.num_microbatches):
            self._in_writer.write(tokens[k * mb:(k + 1) * mb],
                                  check=self._check_failure)
        want = self._local_step + 1
        reports = []
        try:
            for reader in self._rep_readers:
                left = max(0.1, self._step_timeout
                           - (time.perf_counter() - t0))
                value, is_err = reader.read(want, timeout=left,
                                            check=self._check_failure,
                                            copy=True)
                if is_err:
                    raise value
                reader.advance(want)
                reports.append(value)
        except BaseException as e:
            # the microbatch writes already landed, so a retried step()
            # would feed the stages a SECOND batch they treat as the
            # next step — driver and stage sequence state desync with
            # loss attribution silently shifted by one.  Fail the
            # pipeline instead; checkpointed runs recover via resume().
            if self._error is None and not self._torn_down:
                self._fail(e if isinstance(e, RayError) else
                           PipelineError(f"step {want} failed mid-flight "
                                         f"(stage reports unread): {e}"))
            raise
        wall = time.perf_counter() - t0
        self._local_step = want
        self._global_step = reports[-1]["step"]
        busy = [r["busy_s"] for r in reports]
        bubble = bubble_pct(busy, wall)
        gauge, busy_counter = pipeline_metrics()
        for r in reports:
            gauge.set(100.0 * max(0.0, 1.0 - r["busy_s"] / wall)
                      if wall > 0 else 0.0,
                      tags={"stage": str(r["stage"])})
            for phase in ("fwd", "bwd", "opt"):
                busy_counter.inc(r[f"{phase}_s"],
                                 tags={"stage": str(r["stage"]),
                                       "phase": phase})
        gauge.set(bubble, tags={"stage": "all"})
        return {
            "step": self._global_step,
            "loss": reports[-1]["loss"],
            "wall_s": wall,
            "tokens_per_s": B * self.seq_len / wall if wall > 0 else 0.0,
            "bubble_pct": bubble,
            "per_stage": reports,
        }

    # ----------------------------------------------------------- recovery

    def resume(self, timeout: float = 300.0) -> int:
        """After a stage death: roll every stage back to the newest
        COMMON snapshot step, rebuild fresh channels, reinstall the
        loops.  Returns the resumed step.  Requires checkpointing
        (``save_every > 0``) and restartable actors."""
        import ray_tpu
        from ray_tpu import api as _api

        if self._torn_down:
            raise PipelineError("this TrainPipeline has been torn down")
        if self._error is None:
            return self._global_step
        if self._save_every <= 0:
            raise PipelineError(
                "cannot resume without stage checkpointing — construct "
                "with max_restarts>0 (or save_every>0)")
        deadline = time.monotonic() + timeout
        self._monitor_stop.set()
        # old loops are dead or draining after the poison; wait them out
        if self._loop_refs:
            ray_tpu.wait(self._loop_refs, num_returns=len(self._loop_refs),
                         timeout=max(1.0, deadline - time.monotonic()))
        if self._in_writer is not None:
            self._in_writer.detach()
        w = _api._worker()
        for h in self._handles:  # restarted stages must be ALIVE again
            while True:
                try:
                    info = w.head.call("get_actor_info",
                                       actor_id=h._actor_id)
                except Exception as e:
                    raise PipelineError(f"head unreachable: {e}")
                if info.get("state") == "ALIVE":
                    break
                if info.get("state") == "DEAD" \
                        or time.monotonic() >= deadline:
                    raise PipelineError(
                        f"stage actor {h._actor_id[:12]} did not restart "
                        f"(state {info.get('state')})")
                time.sleep(0.2)
        step_sets = []
        for h in self._handles:
            reply = self._ctl(h, {"op": "snapshot_steps"},
                              timeout=max(1.0,
                                          deadline - time.monotonic()))
            step_sets.append(set(reply["steps"]))
        common = sorted(set.intersection(*step_sets)) if step_sets else []
        if not common:
            raise PipelineError(
                f"no common snapshot step across stages: {step_sets}")
        target = common[-1]
        for h in self._handles:
            reply = self._ctl(h, {"op": "load_snapshot", "step": target},
                              timeout=max(1.0,
                                          deadline - time.monotonic()))
            if not reply.get("ok"):
                raise PipelineError(
                    f"stage failed to load snapshot step {target}")
        from ray_tpu.dag.execution import _unregister_live_channels

        _unregister_live_channels(id(self))
        self._channels.destroy_all()
        self._generation += 1
        self._error = None
        self._wire(start_step=target,
                   timeout=max(1.0, deadline - time.monotonic()))
        return target

    # ----------------------------------------------------------- teardown

    def teardown(self, timeout: Optional[float] = None) -> None:
        """Synchronous + idempotent: close channels (loops drain and
        return), kill stage actors, free every pinned slot."""
        import ray_tpu
        from ray_tpu import api as _api
        from ray_tpu._private.config import config

        with self._teardown_lock:
            if self._torn_down:
                return
            self._torn_down = True
        from ray_tpu.dag.execution import _unregister_live_channels

        # this pipeline no longer claims its slots: failed destroys
        # below get flagged leaked by the accounting layer (correctly)
        _unregister_live_channels(id(self))
        self._monitor_stop.set()
        timeout = (float(config.dag_teardown_timeout_s)
                   if timeout is None else timeout)
        deadline = time.monotonic() + timeout
        self._channels.poison_all(close_only=True)
        refs = list(self._loop_refs)
        if refs:
            _ready, pending = ray_tpu.wait(
                refs, num_returns=len(refs),
                timeout=max(0.1, deadline - time.monotonic()))
            for ref in pending:
                try:
                    ray_tpu.cancel(ref, force=True)
                except Exception:
                    pass
        for h in getattr(self, "_handles", []):
            try:
                ray_tpu.kill(h)
            except Exception:
                pass
        try:
            w = _api._worker()
        except Exception:
            w = None
        if w is not None:
            for h in getattr(self, "_handles", []):
                while time.monotonic() < deadline:
                    try:
                        info = w.head.call("get_actor_info",
                                           actor_id=h._actor_id)
                    except Exception:
                        break
                    if info.get("state") == "DEAD":
                        break
                    time.sleep(0.05)
        self._handles = []
        self._channels.destroy_all()
        if self._in_writer is not None:
            self._in_writer.detach()
        self._channels.close()
        if self._monitor is not None \
                and self._monitor is not threading.current_thread():
            self._monitor.join(timeout=1.0)

    def __del__(self):
        try:
            if not self._torn_down:
                self.teardown(timeout=2.0)
        except Exception:
            pass
