"""ray_tpu.tune: hyperparameter search over trial actors.

Equivalent of Ray Tune (reference: python/ray/tune/ — Tuner tuner.py,
TuneController execution/tune_controller.py:69, searchers search/,
schedulers schedulers/): trials are actors running the user trainable
with a report channel; the controller loop launches/polls/stops trials
under a concurrency cap; ASHA prunes at rungs.
"""

from ray_tpu.tune.search import (Domain, choice, grid_search, loguniform,
                                 randint, uniform)
from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     PopulationBasedTraining)
from ray_tpu.tune.tuner import (ResultGrid, TrialResult, TuneConfig, Tuner)

__all__ = ["Tuner", "TuneConfig", "ResultGrid", "TrialResult",
           "grid_search", "choice", "uniform", "loguniform", "randint",
           "ASHAScheduler", "FIFOScheduler", "PopulationBasedTraining",
           "Domain"]
