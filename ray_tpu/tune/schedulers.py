"""Trial schedulers: FIFO and ASHA early stopping.

Equivalent of the reference's schedulers
(reference: python/ray/tune/schedulers/async_hyperband.py ASHA,
trial_scheduler.py decision protocol): on_result returns CONTINUE or
STOP; ASHA prunes trials that fall below the top fraction at each rung.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass


class ASHAScheduler:
    """Asynchronous Successive Halving.

    Rungs at max_t / reduction_factor^k; a trial reaching a rung is
    stopped unless its metric is in the top 1/reduction_factor of all
    results recorded at that rung so far.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung milestone -> list of recorded metric values
        self.rungs: Dict[int, List[float]] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self.milestones = milestones
        self._trial_rungs: Dict[str, int] = {}

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP  # budget exhausted (not a pruning decision)
        decision = CONTINUE
        for rung in self.milestones:
            if t >= rung and self._trial_rungs.get(trial_id, -1) < rung:
                self._trial_rungs[trial_id] = rung
                recorded = self.rungs.setdefault(rung, [])
                recorded.append(float(value))
                if not self._in_top_fraction(float(value), recorded):
                    decision = STOP
        return decision

    def _in_top_fraction(self, value: float, recorded: List[float]) -> bool:
        if len(recorded) < self.rf:
            return True  # not enough evidence to prune yet
        k = max(1, math.floor(len(recorded) / self.rf))
        ordered = sorted(recorded, reverse=(self.mode == "max"))
        cutoff = ordered[k - 1]
        return value <= cutoff if self.mode == "min" else value >= cutoff

    def on_trial_complete(self, trial_id: str) -> None:
        self._trial_rungs.pop(trial_id, None)
