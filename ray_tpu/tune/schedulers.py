"""Trial schedulers: FIFO, ASHA early stopping, and PBT.

Equivalent of the reference's schedulers
(reference: python/ray/tune/schedulers/async_hyperband.py ASHA,
pbt.py PopulationBasedTraining, trial_scheduler.py decision protocol):
on_result returns CONTINUE or STOP; ASHA prunes trials that fall below
the top fraction at each rung; PBT stops bottom-quantile trials and
clones top performers with perturbed configs (the Tuner launches the
clones it pops from the scheduler).
"""

from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional, Union

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass


class ASHAScheduler:
    """Asynchronous Successive Halving.

    Rungs at max_t / reduction_factor^k; a trial reaching a rung is
    stopped unless its metric is in the top 1/reduction_factor of all
    results recorded at that rung so far.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung milestone -> list of recorded metric values
        self.rungs: Dict[int, List[float]] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self.milestones = milestones
        self._trial_rungs: Dict[str, int] = {}

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP  # budget exhausted (not a pruning decision)
        decision = CONTINUE
        for rung in self.milestones:
            if t >= rung and self._trial_rungs.get(trial_id, -1) < rung:
                self._trial_rungs[trial_id] = rung
                recorded = self.rungs.setdefault(rung, [])
                recorded.append(float(value))
                if not self._in_top_fraction(float(value), recorded):
                    decision = STOP
        return decision

    def _in_top_fraction(self, value: float, recorded: List[float]) -> bool:
        if len(recorded) < self.rf:
            return True  # not enough evidence to prune yet
        k = max(1, math.floor(len(recorded) / self.rf))
        ordered = sorted(recorded, reverse=(self.mode == "max"))
        cutoff = ordered[k - 1]
        return value <= cutoff if self.mode == "min" else value >= cutoff

    def on_trial_complete(self, trial_id: str) -> None:
        self._trial_rungs.pop(trial_id, None)


class PopulationBasedTraining:
    """PBT: exploit + explore over a live population
    (reference: tune/schedulers/pbt.py — at each perturbation interval,
    bottom-quantile trials copy a top performer's checkpoint and a
    perturbed copy of its config).

    Runs on the stop-and-clone protocol: a trial chosen to exploit is
    STOPped and the scheduler queues a clone spec — donor config with
    mutations applied, donor checkpoint under "__restore_checkpoint__";
    the Tuner pops clones via pop_clones() and launches them as fresh
    trials, keeping the population size constant.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 2,
                 quantile_fraction: float = 0.25,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 time_attr: str = "training_iteration", seed: int = 0):
        assert mode in ("min", "max")
        assert 0 < quantile_fraction <= 0.5
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.quantile = quantile_fraction
        self.mutations = hyperparam_mutations or {}
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        # trial_id -> latest (score, t, config, checkpoint)
        self._state: Dict[str, Dict[str, Any]] = {}
        self._last_perturb: Dict[str, int] = {}
        self._clones: List[Dict[str, Any]] = []

    def on_trial_state(self, trial_id: str, config: Dict[str, Any],
                       checkpoint: Optional[str]) -> None:
        """Tuner hook: the scheduler needs configs + checkpoints to
        build exploit clones."""
        st = self._state.setdefault(trial_id, {})
        st["config"] = config
        st["checkpoint"] = checkpoint

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        st = self._state.setdefault(trial_id, {})
        st["score"] = float(value)
        st["t"] = int(t)
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = int(t)
        scored = [(tid, s) for tid, s in self._state.items()
                  if "score" in s and s.get("config") is not None]
        k = max(1, int(len(scored) * self.quantile))
        if len(scored) < 2 * k:
            return CONTINUE  # population too small to rank reliably
        ordered = sorted(scored, key=lambda kv: kv[1]["score"],
                         reverse=(self.mode == "max"))
        top = ordered[:k]
        # only live trials can be stopped; finished ones still rank and
        # donate (fast trainables may complete before peers report)
        bottom = {tid for tid, s in ordered[-k:] if not s.get("done")}
        if trial_id not in bottom:
            return CONTINUE
        donor_id, donor = self._rng.choice(top)
        if donor_id == trial_id:
            return CONTINUE
        clone_config = self._explore(dict(donor["config"]))
        clone_config.pop("__restore_checkpoint__", None)
        if donor.get("checkpoint"):
            clone_config["__restore_checkpoint__"] = donor["checkpoint"]
        self._clones.append({"config": clone_config, "exploited": trial_id,
                             "donor": donor_id})
        self._state.pop(trial_id, None)  # replaced; drop from ranking
        return STOP

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Perturb mutated hyperparams by 1.2x/0.8x or resample
        (reference: pbt.py explore())."""
        for key, spec in self.mutations.items():
            if callable(spec):
                config[key] = spec()
            elif isinstance(spec, (list, tuple)):
                config[key] = self._rng.choice(list(spec))
            elif isinstance(config.get(key), (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                val = config[key] * factor
                config[key] = type(config[key])(val) \
                    if isinstance(config[key], int) else val
        return config

    def pop_clones(self) -> List[Dict[str, Any]]:
        out, self._clones = self._clones, []
        return out

    def on_trial_complete(self, trial_id: str) -> None:
        # keep the record: finished trials still rank and donate
        st = self._state.get(trial_id)
        if st is not None:
            st["done"] = True
