"""Tuner + trial controller.

Equivalent of the reference's Tuner/TuneController
(reference: python/ray/tune/tuner.py; execution/tune_controller.py:69 —
step :667 launches trial actors, dispatches train, reacts to results).
Trials reuse the TrainWorker actor (run_async/poll/request_stop), so a
trial IS a 1-worker training run — mirroring the reference where Train
execution *is* Tune execution (base_trainer.py:567).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP
from ray_tpu.tune.search import generate_variants


@dataclass
class TuneConfig:
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    metric: str = "loss"
    mode: str = "min"
    scheduler: Optional[Any] = None
    seed: Optional[int] = None


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    state: str = "PENDING"  # RUNNING/TERMINATED/STOPPED/ERROR
    error: Optional[str] = None
    checkpoint: Optional[str] = None


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self.results = results
        self._metric = metric
        self._mode = mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self.results
                  if r.state in ("TERMINATED", "STOPPED")
                  and metric in r.metrics]
        if not scored:
            raise ValueError("no successful trial reported "
                             f"metric {metric!r}")
        return (min if mode == "min" else max)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self.results:
            row = {"trial_id": r.trial_id, "state": r.state, **r.metrics}
            row.update({f"config/{k}": v for k, v in r.config.items()})
            rows.append(row)
        return pd.DataFrame(rows)

    def __len__(self):
        return len(self.results)

    def __iter__(self):
        return iter(self.results)


class _Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.id = trial_id
        self.config = config
        self.result = TrialResult(trial_id, config)
        self.actor = None
        self.iteration = 0
        self.stopping = False


class Tuner:
    def __init__(self, trainable: Callable[[Dict[str, Any]], Any],
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.resources_per_trial = resources_per_trial

    def fit(self) -> ResultGrid:
        import ray_tpu
        from ray_tpu.train.worker_group import TrainWorker

        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        variants = generate_variants(self.param_space, tc.num_samples, tc.seed)
        trials = [_Trial(f"trial_{i:05d}", cfg)
                  for i, cfg in enumerate(variants)]
        cap = tc.max_concurrent_trials or min(8, max(1, len(trials)))
        fn_blob = cloudpickle.dumps(self.trainable)
        actor_cls = ray_tpu.remote(TrainWorker)
        if self.resources_per_trial:
            actor_cls = actor_cls.options(resources=self.resources_per_trial)

        pending = list(trials)
        running: List[_Trial] = []
        finished: List[_Trial] = []
        while pending or running:
            # launch up to the concurrency cap
            # (reference: _schedule_trial_actor tune_controller.py:965)
            while pending and len(running) < cap:
                t = pending.pop(0)
                try:
                    t.actor = actor_cls.remote(0, 1)
                    t.result.state = "RUNNING"
                    ray_tpu.get(t.actor.run_async.remote(fn_blob, t.config),
                                timeout=120)
                except ray_tpu.RayError as e:
                    # placement failure must cost only this trial, not the
                    # whole experiment's completed results
                    t.result.state = "ERROR"
                    t.result.error = str(e)
                    if t.actor is not None:
                        try:
                            ray_tpu.kill(t.actor)
                        except Exception:
                            pass
                    finished.append(t)
                    continue
                running.append(t)
            time.sleep(0.02)
            for t in list(running):
                try:
                    poll = ray_tpu.get(t.actor.poll.remote(), timeout=60)
                except ray_tpu.RayError as e:
                    t.result.state = "ERROR"
                    t.result.error = str(e)
                    running.remove(t)
                    finished.append(t)
                    continue
                self._ingest(t, poll, scheduler)
                if poll["done"]:
                    if poll["error"] is not None and t.result.state != "STOPPED":
                        t.result.state = "ERROR"
                        t.result.error = repr(cloudpickle.loads(poll["error"]))
                    elif t.result.state == "RUNNING":
                        t.result.state = "TERMINATED"
                    scheduler.on_trial_complete(t.id)
                    running.remove(t)
                    finished.append(t)
                    ray_tpu.kill(t.actor)
        return ResultGrid([t.result for t in finished], tc.metric, tc.mode)

    def _ingest(self, t: _Trial, poll: Dict[str, Any], scheduler) -> None:
        import ray_tpu

        for rep in poll["reports"]:
            metrics = rep["metrics"]
            if "_error" in metrics:
                continue
            t.iteration += 1
            metrics = dict(metrics)
            metrics.setdefault("training_iteration", t.iteration)
            t.result.metrics = metrics
            t.result.metrics_history.append(metrics)
            if rep.get("checkpoint"):
                t.result.checkpoint = rep["checkpoint"]
            if not t.stopping and scheduler.on_result(t.id, metrics) == STOP:
                t.stopping = True
                t.result.state = "STOPPED"
                try:
                    ray_tpu.get(t.actor.request_stop.remote(), timeout=30)
                except ray_tpu.RayError:
                    pass
