"""Tuner + trial controller.

Equivalent of the reference's Tuner/TuneController
(reference: python/ray/tune/tuner.py; execution/tune_controller.py:69 —
step :667 launches trial actors, dispatches train, reacts to results).
Trials reuse the TrainWorker actor (run_async/poll/request_stop), so a
trial IS a 1-worker training run — mirroring the reference where Train
execution *is* Tune execution (base_trainer.py:567).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP
from ray_tpu.tune.search import generate_variants


@dataclass
class TuneConfig:
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    metric: str = "loss"
    mode: str = "min"
    scheduler: Optional[Any] = None
    seed: Optional[int] = None


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    state: str = "PENDING"  # RUNNING/TERMINATED/STOPPED/ERROR
    error: Optional[str] = None
    checkpoint: Optional[str] = None


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self.results = results
        self._metric = metric
        self._mode = mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self.results
                  if r.state in ("TERMINATED", "STOPPED")
                  and metric in r.metrics]
        if not scored:
            raise ValueError("no successful trial reported "
                             f"metric {metric!r}")
        return (min if mode == "min" else max)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self.results:
            row = {"trial_id": r.trial_id, "state": r.state, **r.metrics}
            row.update({f"config/{k}": v for k, v in r.config.items()})
            rows.append(row)
        return pd.DataFrame(rows)

    def __len__(self):
        return len(self.results)

    def __iter__(self):
        return iter(self.results)


class _Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.id = trial_id
        self.config = config
        self.result = TrialResult(trial_id, config)
        self.actor = None
        self.iteration = 0
        self.stopping = False


class Tuner:
    def __init__(self, trainable: Callable[[Dict[str, Any]], Any],
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 storage_path: Optional[str] = None,
                 name: Optional[str] = None,
                 _restored_state: Optional[Dict[str, Any]] = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.resources_per_trial = resources_per_trial
        self.name = name or "tune_experiment"
        self.storage_path = storage_path
        self._restored_state = _restored_state

    # ---- experiment persistence --------------------------------------------
    # Reference: tune/execution/experiment_state.py — periodic experiment
    # snapshots make `Tuner.restore` possible: finished trials keep their
    # results, interrupted ones re-run.

    @property
    def _experiment_dir(self) -> Optional[str]:
        import os

        if not self.storage_path:
            return None
        d = os.path.join(self.storage_path, self.name)
        os.makedirs(d, exist_ok=True)
        return d

    def _save_experiment(self, trials: List["_Trial"]) -> None:
        import os

        d = self._experiment_dir
        if d is None:
            return
        snap = {
            "param_space": self.param_space,
            "tune_config": self.tune_config,
            "trials": [{"id": t.id, "config": t.config, "result": t.result}
                       for t in trials],
        }
        tmp = os.path.join(d, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            f.write(cloudpickle.dumps(snap))
        os.replace(tmp, os.path.join(d, "experiment_state.pkl"))

    @classmethod
    def restore(cls, path: str, trainable: Callable[[Dict[str, Any]], Any],
                *, resources_per_trial: Optional[Dict[str, float]] = None
                ) -> "Tuner":
        """Resume an experiment from its snapshot directory
        (reference: Tuner.restore).  Completed trials keep their
        recorded results; unfinished ones run again."""
        import os

        state_file = os.path.join(path, "experiment_state.pkl")
        with open(state_file, "rb") as f:
            snap = cloudpickle.loads(f.read())
        return cls(trainable,
                   param_space=snap["param_space"],
                   tune_config=snap["tune_config"],
                   resources_per_trial=resources_per_trial,
                   storage_path=os.path.dirname(path.rstrip("/")),
                   name=os.path.basename(path.rstrip("/")),
                   _restored_state=snap)

    def _build_trials(self) -> (List["_Trial"], List["_Trial"]):
        """-> (to_run, already_finished)"""
        tc = self.tune_config
        if self._restored_state is None:
            variants = generate_variants(self.param_space, tc.num_samples,
                                         tc.seed)
            return [_Trial(f"trial_{i:05d}", cfg)
                    for i, cfg in enumerate(variants)], []
        to_run, done = [], []
        for rec in self._restored_state["trials"]:
            t = _Trial(rec["id"], rec["config"])
            if rec["result"].state in ("TERMINATED", "STOPPED"):
                t.result = rec["result"]
                done.append(t)
            else:
                if rec["result"].checkpoint:
                    # interrupted mid-run: resume from its last checkpoint
                    t.config = dict(t.config)
                    t.config["__restore_checkpoint__"] = \
                        rec["result"].checkpoint
                    t.result.checkpoint = rec["result"].checkpoint
                to_run.append(t)
        return to_run, done

    def fit(self) -> ResultGrid:
        import ray_tpu
        from ray_tpu.train.worker_group import TrainWorker

        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        trials, finished_restored = self._build_trials()
        cap = tc.max_concurrent_trials or min(8, max(1, len(trials) or 1))
        fn_blob = cloudpickle.dumps(self.trainable)
        actor_cls = ray_tpu.remote(TrainWorker)
        if self.resources_per_trial:
            actor_cls = actor_cls.options(resources=self.resources_per_trial)

        # resume-safe: continue numbering after any restored clone ids
        clone_counter = max(
            [int(t.id.split("_")[1]) for t in trials + finished_restored
             if t.id.startswith("clone_")] or [0])
        pending = list(trials)
        running: List[_Trial] = []
        finished: List[_Trial] = list(finished_restored)
        dirty = False
        last_save = 0.0
        while pending or running:
            # launch up to the concurrency cap
            # (reference: _schedule_trial_actor tune_controller.py:965)
            while pending and len(running) < cap:
                t = pending.pop(0)
                try:
                    t.actor = actor_cls.remote(0, 1)
                    t.result.state = "RUNNING"
                    ray_tpu.get(t.actor.run_async.remote(fn_blob, t.config),
                                timeout=120)
                except ray_tpu.RayError as e:
                    # placement failure must cost only this trial, not the
                    # whole experiment's completed results
                    t.result.state = "ERROR"
                    t.result.error = str(e)
                    if t.actor is not None:
                        try:
                            ray_tpu.kill(t.actor)
                        except Exception:
                            pass
                    finished.append(t)
                    continue
                running.append(t)
            time.sleep(0.02)
            for t in list(running):
                try:
                    poll = ray_tpu.get(t.actor.poll.remote(), timeout=60)
                except ray_tpu.RayError as e:
                    t.result.state = "ERROR"
                    t.result.error = str(e)
                    running.remove(t)
                    finished.append(t)
                    continue
                if poll["reports"]:
                    dirty = True
                self._ingest(t, poll, scheduler)
                if poll["done"]:
                    if poll["error"] is not None and t.result.state != "STOPPED":
                        t.result.state = "ERROR"
                        t.result.error = repr(cloudpickle.loads(poll["error"]))
                    elif t.result.state == "RUNNING":
                        t.result.state = "TERMINATED"
                    scheduler.on_trial_complete(t.id)
                    running.remove(t)
                    finished.append(t)
                    dirty = True
                    ray_tpu.kill(t.actor)
            # PBT-style schedulers queue clone specs (exploit+explore);
            # launch them as fresh trials to keep the population size
            for spec in (scheduler.pop_clones()
                         if hasattr(scheduler, "pop_clones") else []):
                clone_counter += 1
                clone = _Trial(f"clone_{clone_counter:05d}", spec["config"])
                trials.append(clone)
                pending.append(clone)
                dirty = True
            # debounced: snapshotting pickles every trial's history, so
            # only write when something changed and at most ~1/s
            if dirty and time.monotonic() - last_save >= 1.0:
                dirty = False
                last_save = time.monotonic()
                self._save_experiment(trials + finished_restored)
        self._save_experiment(trials + finished_restored)
        return ResultGrid([t.result for t in finished], tc.metric, tc.mode)

    def _ingest(self, t: _Trial, poll: Dict[str, Any], scheduler) -> None:
        import ray_tpu

        for rep in poll["reports"]:
            metrics = rep["metrics"]
            if "_error" in metrics:
                continue
            t.iteration += 1
            metrics = dict(metrics)
            metrics.setdefault("training_iteration", t.iteration)
            t.result.metrics = metrics
            t.result.metrics_history.append(metrics)
            if rep.get("checkpoint"):
                t.result.checkpoint = rep["checkpoint"]
            if hasattr(scheduler, "on_trial_state"):
                scheduler.on_trial_state(t.id, t.config, t.result.checkpoint)
            if not t.stopping and scheduler.on_result(t.id, metrics) == STOP:
                t.stopping = True
                t.result.state = "STOPPED"
                try:
                    ray_tpu.get(t.actor.request_stop.remote(), timeout=30)
                except ray_tpu.RayError:
                    pass
