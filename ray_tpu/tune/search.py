"""Search-space DSL + basic variant generation.

Equivalent of the reference's sample.py domains and basic_variant.py
(reference: python/ray/tune/search/sample.py, basic_variant.py):
grid_search expands cartesian products; stochastic domains sample per
trial.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class _Choice(Domain):
    def __init__(self, options: Sequence[Any]):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class _Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class _LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class _RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class _Grid:
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def choice(options: Sequence[Any]) -> Domain:
    return _Choice(options)


def uniform(low: float, high: float) -> Domain:
    return _Uniform(low, high)


def loguniform(low: float, high: float) -> Domain:
    return _LogUniform(low, high)


def randint(low: int, high: int) -> Domain:
    return _RandInt(low, high)


def grid_search(values: Sequence[Any]) -> _Grid:
    return _Grid(values)


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: Optional[int] = None) -> List[Dict[str, Any]]:
    """Grid axes expand combinatorially; each combination is repeated
    num_samples times with stochastic domains re-sampled (reference:
    basic_variant.py semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, _Grid)]
    grid_values = [param_space[k].values for k in grid_keys]
    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    out: List[Dict[str, Any]] = []
    for combo in combos:
        for _ in range(num_samples):
            cfg: Dict[str, Any] = {}
            for k, v in param_space.items():
                if isinstance(v, _Grid):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            out.append(cfg)
    return out
