"""CLI: stand clusters up and operate them from the shell.

Equivalent of the reference's `ray` CLI
(reference: python/ray/scripts/scripts.py — start :568, stop :1044,
status; job CLI in dashboard/modules/job/cli.py; summary/state CLI in
python/ray/util/state/state_cli.py).  Installed as `rtpu` via
[project.scripts].

  rtpu start --head [--port N] [--num-cpus N] [--resources JSON]
  rtpu start --address HOST:PORT [--num-cpus N]     # join as a worker node
  rtpu status [--watch] [--address HOST:PORT]
  rtpu stop   [--address HOST:PORT]
  rtpu job submit [--address A] [--working-dir D] -- python train.py
  rtpu job status|logs|stop JOB_ID
  rtpu job list
  rtpu summary [tasks|actors|objects]   # per-function aggregates + percentiles
  rtpu memory [--top N] [--json]        # who owns the cluster's bytes + leaks
  rtpu timeline -o trace.json
  rtpu trace list [--limit N]
  rtpu trace get TRACE_ID [-o trace.json]
  rtpu stack [TARGET]               # live tracebacks: head/agents/workers
  rtpu profile TARGET --duration N  # sampling profiler (collapsed/speedscope)
  rtpu logs [--follow] [--tail N]   # worker logs streamed off the agents

Cluster discovery: `start --head` records the address in
$RT_TMPDIR/latest_cluster.json; other commands use --address,
RT_ADDRESS, or that file, in that order.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional, Tuple


def _registry_path() -> str:
    base = os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, "latest_cluster.json")


def _resolve_address(explicit: Optional[str]) -> Tuple[str, int]:
    addr = explicit or os.environ.get("RT_ADDRESS")
    if not addr:
        try:
            with open(_registry_path()) as f:
                addr = json.load(f)["address"]
        except Exception:
            raise SystemExit(
                "no cluster address: pass --address, set RT_ADDRESS, or "
                "run `rtpu start --head` on this machine first")
    host, port_s = addr.rsplit(":", 1)
    return host, int(port_s)


def _head_client(addr: Tuple[str, int]):
    from ray_tpu._private.rpc import EventLoopThread, SyncRpcClient

    io = EventLoopThread(name="rtpu-cli")
    return SyncRpcClient(addr[0], addr[1], io, label="head"), io


# ---------------------------------------------------------------- start/stop


def cmd_start(args) -> int:
    from ray_tpu._private import node as node_mod

    if not args.head and not args.address:
        print("pass --head to start a cluster or --address to join one",
              file=sys.stderr)
        return 2
    session_dir = node_mod.new_session_dir()
    if args.head:
        head_proc, head_addr = node_mod.start_head(session_dir,
                                                   port=args.port)
        res = node_mod.default_resources(args.num_cpus,
                                         json.loads(args.resources))
        agent_proc, info = node_mod.start_node_agent(
            session_dir, head_addr, res,
            object_store_memory=args.object_store_memory,
            is_head_node=True)
        address = f"{head_addr[0]}:{head_addr[1]}"
        with open(_registry_path(), "w") as f:
            json.dump({"address": address, "session_dir": session_dir,
                       "head_pid": head_proc.proc.pid,
                       "agent_pids": [agent_proc.proc.pid]}, f)
        print(f"cluster started at {address}")
        print(f"session dir: {session_dir}")
        print(f"connect with ray_tpu.init(address=\"{address}\") "
              f"or RT_ADDRESS={address}")
    else:
        head_addr = _resolve_address(args.address)
        res = node_mod.default_resources(args.num_cpus,
                                         json.loads(args.resources))
        _, info = node_mod.start_node_agent(
            session_dir, head_addr, res,
            object_store_memory=args.object_store_memory)
        print(f"node {info['node_id'][:12]} joined "
              f"{head_addr[0]}:{head_addr[1]}")
    return 0


def cmd_stop(args) -> int:
    addr = _resolve_address(args.address)
    head, io = _head_client(addr)
    try:
        head.call("shutdown_cluster", timeout=10)
        print("cluster shutdown requested")
    except Exception as e:
        print(f"head unreachable ({e}); nothing to stop?", file=sys.stderr)
        return 1
    finally:
        head.close()
        io.stop()
    try:
        os.unlink(_registry_path())
    except OSError:
        pass
    return 0


def _print_status(addr, head) -> None:
    table = head.call("node_table", timeout=10)
    res = head.call("cluster_resources", timeout=10)
    auto = head.call("autoscaler_state", timeout=10)
    print(f"cluster at {addr[0]}:{addr[1]} — {len(table)} node(s)")
    for nid, n in table.items():
        r = n["resources"]
        role = " (head)" if n.get("is_head_node") else ""
        print(f"  {nid[:12]}{role}  total={r['total']}  "
              f"available={r['available']}")
    print(f"resources: total={res['total']} available={res['available']}")
    pending = sum(len(n["pending"]) for n in auto["nodes"])
    if pending or auto["pending_pg_bundles"] or auto["pending_actors"]:
        print(f"pending demands: {pending} lease(s), "
              f"{len(auto['pending_pg_bundles'])} pg bundle(s), "
              f"{len(auto['pending_actors'])} actor(s)")
    _print_shards(head)
    _print_autoscaler(head)


def _print_shards(head) -> None:
    """Head ingest shard pane: which planes run on their own loop and
    how laggy each loop is — the first place to look when the head
    feels slow (count 0 = single-loop compat mode)."""
    try:
        snap = head.call("autoscaler_snapshot", timeout=10)
    except Exception:
        return
    sh = snap.get("shards") or {}
    planes = sh.get("planes") or {}
    if not planes:
        return
    parts = []
    for name, p in sorted(planes.items()):
        where = "own loop" if p.get("own_thread") else "head loop"
        part = f"{name}={where} lag {p.get('lag_s', 0) * 1000:.1f}ms"
        if p.get("dropped"):
            part += f" dropped {p['dropped']}"
        parts.append(part)
    print(f"head ingest shards: {sh.get('count', 0)}  " + "  ".join(parts))


def _print_autoscaler(head) -> None:
    """Autoscaler pane: pending launches, draining nodes, the last
    decision and live/finished drain records (also at /api/autoscaler)
    — the debuggability surface for scale events."""
    try:
        st = head.call("autoscaler_status", timeout=10)
    except Exception:
        return
    report = st.get("report") or {}
    draining = st.get("draining") or []
    drains = st.get("drains") or {}
    if not report and not draining and not drains \
            and not st.get("registered_types"):
        return  # no autoscaler attached: keep status terse
    print("autoscaler:")
    if st.get("registered_types"):
        types = ", ".join(sorted(st["registered_types"]))
        print(f"  node types: {types}")
    if report:
        print(f"  pending launches: {report.get('pending_launches', 0)}  "
              f"scale events: up={report.get('scale_up_total', 0)} "
              f"down={report.get('scale_down_total', 0)}")
        if report.get("last_decision"):
            print(f"  last decision: {report['last_decision']}")
    if draining:
        print(f"  draining now: {', '.join(n[:12] for n in draining)}")
    for nid, rec in list(drains.items())[-4:]:
        extra = ""
        if rec.get("state") == "drained":
            extra = (f" in {rec.get('drain_s', 0)}s, "
                     f"{rec.get('migrated_actors', 0)} actor(s) migrated, "
                     f"{rec.get('replicated_objects', 0)} object(s) "
                     f"re-replicated")
        elif rec.get("detail"):
            extra = f": {rec['detail']}"
        print(f"  drain {nid[:12]}: {rec.get('state')}"
              f"/{rec.get('phase', '')}{extra}")


def _print_timeseries(head) -> None:
    """Latest value (+ tiny text sparkline) per head time-series —
    the `status --watch` health pane."""
    blocks = " ▁▂▃▄▅▆▇█"
    series = head.call("timeseries", timeout=10).get("series") or []
    if not series:
        return
    print("gauges (head time-series ring):")
    for s in series:
        pts = [v for _, v in s.get("points") or []]
        if not pts:
            continue
        lo, hi = min(pts), max(pts)
        span = (hi - lo) or 1.0
        spark = "".join(
            blocks[int((v - lo) / span * (len(blocks) - 1))]
            for v in pts[-30:])
        print(f"  {s['name']:<24} @{s['node']:<12} "
              f"{pts[-1]:>12.6g}  {spark}")


def cmd_status(args) -> int:
    addr = _resolve_address(args.address)
    head, io = _head_client(addr)
    try:
        while True:
            if args.watch:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear, home
            _print_status(addr, head)
            _print_timeseries(head)
            if not args.watch:
                return 0
            sys.stdout.flush()
            try:
                time.sleep(args.interval)
            except KeyboardInterrupt:
                return 0
    finally:
        head.close()
        io.stop()


# ------------------------------------------------------- live introspection


def cmd_stack(args) -> int:
    """Live stack dumps for every process in the cluster (or one node /
    worker / the head via TARGET) — `ray stack` equivalent, no py-spy."""
    addr = _resolve_address(args.address)
    head, io = _head_client(addr)
    try:
        out = head.call("cluster_stack", target=args.target or "",
                        timeout=30)
    finally:
        head.close()
        io.stop()
    shown = 0

    def _one(title: str, payload) -> None:
        nonlocal shown
        if not isinstance(payload, dict):
            return
        if payload.get("error"):
            print(f"==== {title}: unreachable ({payload['error']}) ====")
            return
        print(f"==== {title} (pid {payload.get('pid')}) ====")
        print(payload.get("text", ""))
        shown += 1

    if "head" in out:
        _one("head", out["head"])
    want_worker = args.target or ""
    for nid, node in (out.get("nodes") or {}).items():
        if not isinstance(node, dict) or node.get("error"):
            print(f"==== node {nid[:12]}: unreachable "
                  f"({node.get('error') if isinstance(node, dict) else node})"
                  f" ====")
            continue
        workers = node.get("workers") or {}
        worker_only = (want_worker
                       and not nid.startswith(want_worker)
                       and want_worker != "head")
        if not worker_only:
            _one(f"node {nid[:12]} agent", node.get("agent") or {})
        for wid, w in workers.items():
            if worker_only and not wid.startswith(want_worker):
                continue
            _one(f"node {nid[:12]} worker {wid[:12]}", w)
    if shown == 0:
        print(f"no process matched target {args.target!r}", file=sys.stderr)
        return 1
    return 0


def cmd_profile(args) -> int:
    """Run the in-process sampling profiler on a target process and
    print (or save) the collapsed stacks / speedscope JSON."""
    addr = _resolve_address(args.address)
    head, io = _head_client(addr)
    try:
        reply = head.call("profile_target", target=args.target,
                          hz=args.hz, duration_s=args.duration,
                          fmt=args.format,
                          timeout=args.duration + 60)
    finally:
        head.close()
        io.stop()
    if not reply.get("ok"):
        print(f"profile failed: {reply.get('error', reply)}",
              file=sys.stderr)
        return 1
    print(f"profiled pid {reply.get('pid')} at {reply.get('hz')}Hz for "
          f"{reply.get('duration_s')}s ({reply.get('samples')} samples)",
          file=sys.stderr)
    if args.output:
        with open(args.output, "w") as f:
            f.write(reply["profile"])
        print(f"wrote {args.format} profile to {args.output}",
              file=sys.stderr)
    else:
        sys.stdout.write(reply["profile"])
        if not reply["profile"].endswith("\n"):
            sys.stdout.write("\n")
    return 0


def _print_log_batch(node_id: str, batch) -> None:
    for ent in batch or []:
        prefix = f"(pid={ent.get('pid')}, node={node_id[:12]}) "
        for line in ent.get("lines") or []:
            print(prefix + line)


def cmd_logs(args) -> int:
    """Tail worker logs across the cluster; with --follow, subscribe to
    every node agent's log monitor and stream increments live."""
    from ray_tpu._private.rpc import EventLoopThread, SyncRpcClient

    addr = _resolve_address(args.address)
    head, io = _head_client(addr)
    agents = []
    try:
        table = head.call("node_table", timeout=10)
        head.close()
        for nid, entry in table.items():
            ahost, aport = entry["addr"]

            def on_push(method, payload, _nid=nid):
                if method == "log_lines":
                    _print_log_batch(payload.get("node_id", _nid),
                                     payload.get("batch"))

            client = SyncRpcClient(ahost, aport, io,
                                   label=f"agent-{nid[:8]}",
                                   on_push=on_push if args.follow else None)
            agents.append((nid, client))
        if not agents:
            print("no nodes registered", file=sys.stderr)
            return 1
        if not args.follow:
            for nid, client in agents:
                reply = client.call("tail_logs", lines=args.tail, timeout=10)
                _print_log_batch(reply.get("node_id", nid),
                                 reply.get("batch"))
            return 0
        for nid, client in agents:
            reply = client.call("subscribe_logs", tail=args.tail, timeout=10)
            _print_log_batch(reply.get("node_id", nid),
                             reply.get("backlog"))
        print("-- following (Ctrl-C to stop) --", file=sys.stderr)
        try:
            while True:
                time.sleep(0.5)
        except KeyboardInterrupt:
            return 0
    finally:
        head.close()
        for _, client in agents:
            client.close()
        io.stop()


# ---------------------------------------------------------------------- jobs


def cmd_job(args) -> int:
    from ray_tpu.job_submission import JobSubmissionClient

    addr = _resolve_address(args.address)
    client = JobSubmissionClient(f"{addr[0]}:{addr[1]}")
    try:
        if args.job_cmd == "submit":
            entrypoint = " ".join(args.entrypoint)
            if not entrypoint:
                print("nothing to run: rtpu job submit -- python x.py",
                      file=sys.stderr)
                return 2
            job_id = client.submit_job(
                entrypoint, working_dir=args.working_dir or None)
            print(f"submitted {job_id}")
            if args.wait:
                status = client.wait_until_finish(job_id)
                print(f"{job_id}: {status}")
                sys.stdout.write(client.get_job_logs(job_id))
                return 0 if status == "SUCCEEDED" else 1
            return 0
        if args.job_cmd == "status":
            print(json.dumps(client.get_job_info(args.job_id), indent=2))
            return 0
        if args.job_cmd == "logs":
            sys.stdout.write(client.get_job_logs(args.job_id))
            return 0
        if args.job_cmd == "stop":
            client.stop_job(args.job_id)
            print(f"stop requested for {args.job_id}")
            return 0
        if args.job_cmd == "list":
            for info in client.list_jobs():
                print(f"{info['job_id']}  {info['status']:<10} "
                      f"{info.get('entrypoint', '')[:60]}")
            return 0
    finally:
        client.close()
    return 2


# ----------------------------------------------------------- state/summary


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def _fmt_pct(p) -> str:
    if not p:
        return "-"
    return (f"n={p['count']} p50={p['p50_ms']:.1f}ms "
            f"p99={p['p99_ms']:.1f}ms")


def cmd_summary(args) -> int:
    """Per-function task aggregates (state counts + queued/running
    percentiles), actor rollups, and the per-node object-store byte
    rollup — straight off the head, no driver attach."""
    head, io = _head_client(_resolve_address(args.address))
    try:
        s = head.call("cluster_summary", timeout=30)
    finally:
        head.close()
        io.stop()
    if args.json:
        print(json.dumps(s, indent=2, default=str))
        return 0
    if args.what in ("tasks", "all"):
        print("tasks (per function):")
        rows = sorted(s["tasks"].items(),
                      key=lambda kv: -sum(kv[1]["states"].values()))
        for name, row in rows:
            states = " ".join(f"{k}={v}" for k, v in
                              sorted(row["states"].items()))
            print(f"  {name[:48]:<48} [{row['kind']}] {states}")
            print(f"    queued:  {_fmt_pct(row.get('queued'))}")
            print(f"    running: {_fmt_pct(row.get('running'))}")
    if args.what in ("actors", "all"):
        a = s["actors"]
        print(f"actors: {a['num_actors']} total, by state "
              f"{a['by_state']}")
        for m, n in sorted(a["methods"].items(), key=lambda kv: -kv[1]):
            print(f"  method {m[:48]:<48} calls={n}")
    if args.what in ("objects", "all"):
        o = s["objects"]
        print(f"objects: {o['total_objects']} in store, "
              f"arena={_fmt_bytes(o['total_arena_used'])} "
              f"pinned={_fmt_bytes(o['total_pinned_bytes'])} "
              f"spilled={_fmt_bytes(o['total_spilled_bytes'])} "
              f"channels={_fmt_bytes(o['total_channel_bytes'])}")
        for nid, m in o["nodes"].items():
            print(f"  node {nid[:12]}: "
                  f"arena {_fmt_bytes(m.get('arena_used'))}/"
                  f"{_fmt_bytes(m.get('capacity'))}, "
                  f"{m.get('num_objects', 0)} objects, "
                  f"pinned {_fmt_bytes(m.get('pinned_bytes'))}, "
                  f"spilled {_fmt_bytes(m.get('spilled_bytes'))} "
                  f"({m.get('spilled_files', 0)} files)")
    scan = s.get("last_leak_scan") or {}
    stale = " (held from last complete scan — view currently partial)" \
        if scan.get("partial") else ""
    if scan.get("leaked_bytes"):
        print(f"LEAKS: {_fmt_bytes(scan['leaked_bytes'])} flagged "
              f"({scan.get('counts')}){stale} — run `rtpu memory` "
              f"for detail")
    elif scan.get("partial"):
        print("LEAKS: detection suspended (partial ownership join) — "
              "run `rtpu memory` for the gap list")
    return 0


def cmd_memory(args) -> int:
    """`rtpu memory`: the joined cluster memory view — per-node byte
    breakdowns, top objects by size with owner + creation call-site,
    and the leak tripwire section (reference: `ray memory`)."""
    head, io = _head_client(_resolve_address(args.address))
    try:
        v = head.call("memory_view", top_n=args.top, timeout=60)
    finally:
        head.close()
        io.stop()
    if args.json:
        print(json.dumps(v, indent=2, default=str))
        return 0
    for nid, b in v["nodes"].items():
        print(f"node {nid[:12]}: arena {_fmt_bytes(b.get('arena_used'))}"
              f"/{_fmt_bytes(b.get('capacity'))} "
              f"({b.get('num_objects', 0)} objects) | "
              f"pinned {_fmt_bytes(b.get('pinned_bytes'))} | "
              f"channels {b.get('channel_slots', 0)} slots "
              f"{_fmt_bytes(b.get('channel_bytes'))} | "
              f"spilled {_fmt_bytes(b.get('spilled_bytes'))} "
              f"({b.get('spilled_files', 0)} files) | "
              f"mmap cache {_fmt_bytes(b.get('mmap_cache_bytes'))} | "
              f"{b.get('inflight_pulls', 0)} pulls in flight")
    attributed, total = v["attributed_bytes"], v["store_object_bytes"]
    pct = 100.0 * attributed / total if total else 100.0
    print(f"{v['num_objects']} store objects, "
          f"{_fmt_bytes(total)} payload bytes, "
          f"{pct:.1f}% attributed to live owners")
    if v.get("errors"):
        # the gap list `rtpu summary` points operators at: who could
        # not be joined and why the view is partial
        print(f"PARTIAL VIEW — {len(v['errors'])} gap(s):")
        for e in v["errors"]:
            print(f"  {e}")
    if v["objects"]:
        print(f"top {len(v['objects'])} objects:")
        print(f"  {'object':<20} {'size':>10} {'node':<12} {'loc':<5} "
              f"{'pins':>4}  owner / call-site")
        # "(no live owner)" is only trustworthy on a complete join — on
        # a partial one the owner may simply be unreachable/truncated
        no_owner = ("(owner unknown — partial view)"
                    if (v.get("leaks") or {}).get("partial")
                    else "(no live owner)")
        for o in v["objects"]:
            own = o.get("owner") or {}
            who = (f"{own.get('kind', '?')}:"
                   f"{own.get('worker_id', '')[:8]} "
                   f"{own.get('name', '')} @ {own.get('call_site', '')}"
                   if own else no_owner)
            flags = "C" if o.get("channel") else ""
            print(f"  {o['object_id'][:20]:<20} "
                  f"{_fmt_bytes(o['size']):>10} {o['node_id'][:12]:<12} "
                  f"{o['location']:<5} {o.get('pins', 0):>4}{flags:<1} {who}")
    leaks = v["leaks"]
    n_leaks = sum(len(leaks[k]) for k in
                  ("dead_owner", "borrowed_ttl", "channel_slots"))
    if n_leaks:
        print(f"leaks ({_fmt_bytes(leaks['leaked_bytes'])} past "
              f"{leaks['ttl_s']}s TTL"
              + (", PARTIAL view" if leaks.get("partial") else "") + "):")
        for e in leaks["dead_owner"]:
            print(f"  dead-owner  {e['object_id'][:20]} "
                  f"{_fmt_bytes(e['size'])} on {e['node_id'][:12]} "
                  f"age={e['age_s']:.0f}s pins={e.get('pins', 0)}")
        for e in leaks["borrowed_ttl"]:
            print(f"  borrowed    {e['object_id'][:20]} "
                  f"held by {e['worker_id'][:12]} age={e['age_s']:.0f}s")
        for e in leaks["channel_slots"]:
            print(f"  channel     {e['object_id'][:20]} "
                  f"{_fmt_bytes(e['size'])} on {e['node_id'][:12]} "
                  f"age={e['age_s']:.0f}s")
    else:
        print("no leaks flagged"
              + (" (partial view)" if leaks.get("partial") else ""))
    return 0


def cmd_chaos(args) -> int:
    """`rtpu chaos inject|schedule|clear|status`: drive the cluster's
    fault-injection plane through the head's chaos RPC (the head applies
    rules locally and gossips them to every agent)."""
    head, io = _head_client(_resolve_address(args.address))
    try:
        if args.chaos_cmd == "inject":
            reply = head.call("chaos", op="inject", rule={
                "site": args.site, "action": args.action, "p": args.p,
                "count": args.count, "delay_s": args.delay,
                "target": args.target, "seed": args.seed})
        elif args.chaos_cmd == "schedule":
            reply = head.call(
                "chaos", op="schedule", seed=args.seed,
                sites=[s for s in args.sites.split(",") if s],
                events_per_site=args.events_per_site, span=args.span)
        elif args.chaos_cmd == "clear":
            reply = head.call("chaos", op="clear")
        else:
            reply = head.call("chaos", op="status")
        print(json.dumps(reply, indent=2))
    finally:
        head.close()
        io.stop()
    return 0


def cmd_quarantine(args) -> int:
    """`rtpu quarantine [list|clear [KEY]]`: inspect and lift the head's
    poison-task quarantine (classes whose executions OOM-killed or
    crashed workers poison_task_threshold consecutive times; their
    submissions fail fast with PoisonedTaskError until the TTL expires
    or this clears them)."""
    head, io = _head_client(_resolve_address(args.address))
    try:
        if args.quarantine_cmd == "clear":
            reply = head.call("quarantine", op="clear", key=args.key)
            print(json.dumps(reply, indent=2))
            return 0
        reply = head.call("quarantine", op="list")
        entries = reply.get("entries", {})
        if not entries:
            print("no task classes under quarantine or kill watch")
            return 0
        for key, e in sorted(entries.items(),
                             key=lambda kv: -kv[1]["kills"]):
            state = (f"QUARANTINED ({e['expires_in_s']}s left)"
                     if e["quarantined"] else "watching")
            print(f"{key[:16]:17} {e['name'] or '?':24} "
                  f"kills={e['kills']:<3} {state}")
            for h in e.get("history", []):
                print(f"                  - {h}")
    finally:
        head.close()
        io.stop()
    return 0


def cmd_trace(args) -> int:
    """Inspect distributed traces straight off the head's trace store
    (no driver attach needed — plain head RPCs)."""
    addr = _resolve_address(args.address)
    head, io = _head_client(addr)
    try:
        if args.trace_cmd == "list":
            reply = head.call("list_traces", limit=args.limit, timeout=10)
            traces = reply["traces"]
            if not traces:
                print("no traces recorded (tracing disabled, sampled "
                      "out, or nothing ran yet)")
                return 0
            for t in traces:
                print(f"{t['trace_id']}  spans={t['num_spans']:<4} "
                      f"dur={t['duration_s'] * 1000:8.1f}ms  "
                      f"root={t.get('root', '')}")
            if reply.get("spans_dropped"):
                print(f"(head dropped {reply['spans_dropped']} spans "
                      f"over the per-trace cap)", file=sys.stderr)
            return 0
        reply = head.call("get_trace", trace_id=args.trace_id, timeout=10)
        if not reply.get("found"):
            print(f"no trace {args.trace_id!r}", file=sys.stderr)
            return 1
        blob = json.dumps(reply["trace"], indent=2, default=str)
        if args.output:
            with open(args.output, "w") as f:
                f.write(blob)
            print(f"wrote {len(reply['trace']['spans'])} spans to "
                  f"{args.output}")
        else:
            print(blob)
        return 0
    finally:
        head.close()
        io.stop()


def cmd_timeline(args) -> int:
    import ray_tpu

    addr = _resolve_address(args.address)
    ray_tpu.init(address=f"{addr[0]}:{addr[1]}")
    try:
        from ray_tpu.util.state import timeline

        events = timeline(args.output)
        print(f"wrote {len(events)} events to {args.output}")
    finally:
        ray_tpu.shutdown()
    return 0


# ----------------------------------------------------------------- argparse


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rtpu",
                                 description="ray_tpu cluster CLI")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or join a cluster")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default="")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--resources", default="{}")
    p.add_argument("--object-store-memory", type=int, default=None)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="shut the cluster down")
    p.add_argument("--address", default="")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="nodes, resources, pending demand")
    p.add_argument("--address", default="")
    p.add_argument("--watch", action="store_true",
                   help="refresh continuously with the head's gauge series")
    p.add_argument("--interval", type=float, default=2.0)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("stack",
                       help="live stack dumps of cluster processes")
    p.add_argument("target", nargs="?", default="",
                   help='"head", a node id prefix, or a worker id prefix')
    p.add_argument("--address", default="")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("profile", help="sampling-profile one process")
    p.add_argument("target",
                   help='"head", a node id prefix, or a worker id prefix')
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--hz", type=float, default=0,
                   help="sampling rate (default: profiler_default_hz)")
    p.add_argument("--format", choices=["collapsed", "speedscope"],
                   default="collapsed")
    p.add_argument("-o", "--output", default="")
    p.add_argument("--address", default="")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("logs", help="tail worker logs across the cluster")
    p.add_argument("--follow", "-f", action="store_true",
                   help="stream new lines as they appear")
    p.add_argument("--tail", type=int, default=100,
                   help="backlog lines per file")
    p.add_argument("--address", default="")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("job", help="submit and manage jobs")
    p.add_argument("--address", default="")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--working-dir", default="")
    js.add_argument("--wait", action="store_true",
                    help="block until the job finishes, stream its logs")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="-- command to run")
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("job_id")
    jsub.add_parser("list")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("summary", help="task/actor/object summaries "
                                       "(state counts + percentiles)")
    p.add_argument("what", nargs="?", default="all",
                   choices=["all", "tasks", "actors", "objects"])
    p.add_argument("--json", action="store_true")
    p.add_argument("--address", default="")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("memory", help="cluster memory/object accounting "
                                      "with owners, call-sites, and leaks")
    p.add_argument("--top", type=int, default=0,
                   help="objects in the top-N table "
                        "(default: memory_view_top_n)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--address", default="")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("timeline", help="export a Chrome trace")
    p.add_argument("-o", "--output", default="timeline.json")
    p.add_argument("--address", default="")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "chaos", help="deterministic fault injection (chaos engineering)")
    p.add_argument("--address", default="")
    csub = p.add_subparsers(dest="chaos_cmd", required=True)
    ci = csub.add_parser("inject", help="arm one fault-injection rule")
    ci.add_argument("--site", required=True,
                    help="rpc.send|rpc.recv|xfer.send|lease.grant|"
                         "worker.kill|worker.stall|agent.kill|head.kill")
    ci.add_argument("--action", required=True,
                    help="drop|delay|sever|truncate|corrupt|kill|stall")
    ci.add_argument("--p", type=float, default=1.0,
                    help="firing probability per matching invocation")
    ci.add_argument("--count", type=int, default=-1,
                    help="max firings PER PROCESS (-1 = unlimited): every "
                         "agent enforces its own cap — scope cluster-wide "
                         "one-shots with --target")
    ci.add_argument("--delay", type=float, default=0.05,
                    help="seconds, for --action delay")
    ci.add_argument("--target", default="",
                    help="substring match on the site key "
                         "(worker id, node id, method, oid)")
    ci.add_argument("--seed", type=int, default=None)
    cs = csub.add_parser(
        "schedule", help="compile a seed into a reproducible failure "
                         "schedule across sites")
    cs.add_argument("--seed", type=int, required=True)
    cs.add_argument("--sites", default="rpc.send,rpc.recv",
                    help="comma-separated site list")
    cs.add_argument("--events-per-site", type=int, default=3)
    cs.add_argument("--span", type=int, default=100,
                    help="invocation horizon the events land in")
    csub.add_parser("clear", help="disarm every rule cluster-wide")
    csub.add_parser("status", help="live rule set + firing counts")
    p.set_defaults(fn=cmd_chaos)

    p = sub.add_parser(
        "quarantine",
        help="poison-task quarantine: list kill watch, clear entries")
    p.add_argument("--address", default="")
    qsub = p.add_subparsers(dest="quarantine_cmd")
    qsub.add_parser("list", help="kill counts + quarantined classes")
    qc = qsub.add_parser("clear",
                         help="lift quarantines now (before the TTL)")
    qc.add_argument("key", nargs="?", default="",
                    help="function/class id to clear ('' = all tripped)")
    p.set_defaults(fn=cmd_quarantine, quarantine_cmd="list")

    p = sub.add_parser("trace", help="inspect distributed traces")
    p.add_argument("--address", default="")
    tsub = p.add_subparsers(dest="trace_cmd", required=True)
    tl = tsub.add_parser("list", help="recent traces, newest first")
    tl.add_argument("--limit", type=int, default=20)
    tg = tsub.add_parser("get", help="dump one trace's spans as JSON")
    tg.add_argument("trace_id")
    tg.add_argument("-o", "--output", default="")
    p.set_defaults(fn=cmd_trace)

    args = ap.parse_args(argv)
    # strip a leading "--" from REMAINDER entrypoints
    if getattr(args, "entrypoint", None) and args.entrypoint[0] == "--":
        args.entrypoint = args.entrypoint[1:]
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
