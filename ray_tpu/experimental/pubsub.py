"""Cluster pub/sub over named channels, backed by the head service.

Equivalent of the reference's pub/sub layer
(reference: src/ray/pubsub/publisher.h:307 + subscriber.h — typed
channels carrying node events, actor state, and error info).  Built-in
channels the head publishes to:

  node_events   — {"event": "registered"|"dead", "node_id", ...}
  actor_events  — {"actor_id", "state": ALIVE|RESTARTING|DEAD, ...}
  error_info    — {"kind": "worker_died", "worker_id", "reason", ...}

Any other channel name works for application events via publish().
Events live in a 1000-entry ring per channel; a slow subscriber that
falls further behind than that misses the overwritten events (same
bounded-buffer semantics as the reference's publisher).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional


def _head():
    import ray_tpu

    return ray_tpu.api._worker().head


def publish(channel: str, payload: Any) -> int:
    """Publish an event; returns its sequence number."""
    return _head().call("publish", channel=channel, payload=payload)["seq"]


def poll(channel: str, after_seq: int = 0,
         timeout_s: float = 0.0) -> List[Dict[str, Any]]:
    """Events with seq > after_seq; blocks up to timeout_s when empty."""
    reply = _head().call(
        "subscribe_poll", channel=channel, after_seq=after_seq,
        timeout_ms=int(timeout_s * 1000),
        timeout=timeout_s + 30.0)
    return reply["events"]


def latest_seq(channel: str) -> int:
    return _head().call("subscribe_poll", channel=channel,
                        after_seq=1 << 60, timeout_ms=0)["latest_seq"]


def listen(channel: str, from_seq: Optional[int] = None,
           poll_timeout_s: float = 10.0,
           stop_after_idle_s: Optional[float] = None) -> Iterator[Dict[str, Any]]:
    """Generator yielding events as they arrive.  Starts at the current
    tail unless from_seq is given.  Stops after stop_after_idle_s of
    silence (None = forever)."""
    seq = latest_seq(channel) if from_seq is None else from_seq
    last_event = time.monotonic()
    while True:
        events = poll(channel, after_seq=seq, timeout_s=poll_timeout_s)
        if events:
            last_event = time.monotonic()
            for e in events:
                seq = max(seq, e["seq"])
                yield e
        elif (stop_after_idle_s is not None
              and time.monotonic() - last_event >= stop_after_idle_s):
            return
