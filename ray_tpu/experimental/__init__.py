from ray_tpu.experimental import internal_kv  # noqa: F401
