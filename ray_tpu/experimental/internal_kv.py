"""Cluster-wide internal key-value store, backed by the head service.

Equivalent of the reference's internal KV
(reference: python/ray/experimental/internal_kv.py; server side
gcs_service.proto:522 InternalKVGcsService).  Carries the function
table, serve controller checkpoints, and collective rendezvous; user
code may use it for small cluster-global metadata (values ride the
control plane — keep them small, bulk data belongs in the object
store).
"""

from __future__ import annotations

from typing import List, Optional, Union


def _key(k: Union[str, bytes]) -> str:
    return k.decode() if isinstance(k, bytes) else k


def _head():
    import ray_tpu

    return ray_tpu.api._worker().head


def kv_put(key: Union[str, bytes], value: Union[str, bytes],
           overwrite: bool = True) -> bool:
    """Returns True if the key was newly added."""
    if isinstance(value, str):
        value = value.encode()
    return _head().call("kv_put", key=_key(key), value=value,
                        overwrite=overwrite)["added"]


def kv_get(key: Union[str, bytes]) -> Optional[bytes]:
    return _head().call("kv_get", key=_key(key))["value"]


def kv_del(key: Union[str, bytes]) -> bool:
    return _head().call("kv_del", key=_key(key))["deleted"]


def kv_exists(key: Union[str, bytes]) -> bool:
    return kv_get(key) is not None


def kv_list(prefix: Union[str, bytes] = "") -> List[str]:
    return _head().call("kv_keys", prefix=_key(prefix))["keys"]


# reference-compatible aliases
_internal_kv_put = kv_put
_internal_kv_get = kv_get
_internal_kv_del = kv_del
_internal_kv_exists = kv_exists
_internal_kv_list = kv_list
